"""Telemetry export surface: OpenMetrics text, delta rates, /metrics.

The registry (profiler/metrics.py) is in-process data; a fleet router
or Prometheus scraper needs it on a wire. Three pieces:

- ``render_prometheus()`` — the whole registry (or one prefix family)
  as OpenMetrics/Prometheus text exposition: counters as ``_total``,
  gauges plain, histograms as cumulative ``_bucket{le=...}`` series
  with ``_sum``/``_count`` — and bucket **exemplars**
  (``# {trace_id="..."} value ts``) linking SLO histograms to
  exportable traces (profiler/tracing.py).
- ``DeltaRates`` — successive snapshots diffed into per-second rates
  (counters and histogram counts), what a watcher plots without
  keeping its own state.
- ``MetricsServer`` — a stdlib ``http.server`` endpoint:

  =====================  ==============================================
  ``/metrics``           OpenMetrics text (scrape me)
  ``/metrics/delta``     JSON per-second rates since the last delta call
  ``/healthz``           JSON liveness + the serving SLO gauges
  ``/readyz``            JSON routability: 200 only while the attached
                         engine's lifecycle is READY (503 in WARMING /
                         DRAINING / CLOSED) — distinct from liveness
  ``/alerts``            JSON active/resolved SLO burn-rate incidents
                         (profiler/alerts.py AlertManager, when attached)
  ``/summary``           the profiler.summary_text() human view (plain
                         text; serving/SLO, capacity, overload, and
                         scenario-scorecard sections included)
  ``/traces``            whole span ring, Chrome/Perfetto JSON
  ``/traces/<trace_id>`` one trace, Chrome/Perfetto JSON (404 unknown)
  =====================  ==============================================

  ``ServingEngine.serve_metrics()`` attaches one to a live engine so
  its ``/healthz`` reflects engine state (closed / died) and its
  ``/readyz`` the drain lifecycle, which is what a multi-replica
  router health-checks and drains against (profiler/fleet.py).

``parse_prometheus()`` round-trips the exposition for gates and tests
(tools/trace_gate.py scrapes, parses, and diffs against snapshot()).
It is label-aware: a sample carrying labels beyond ``le`` (the fleet
aggregator's per-replica series) keys as ``name{k="v"}`` with the
label dict preserved, so a merged fleet exposition round-trips too;
``render_parsed()`` is the inverse — parsed/merged plain data back to
exposition text. Every full (un-prefixed) render also carries one
``replica_info`` gauge whose labels are this process's identity
(profiler/metrics.replica_identity), so any scrape is attributable.
"""

from __future__ import annotations

import json
import re
import threading
import time

from . import metrics as _metrics
from . import tracing as _tracing

__all__ = ["render_prometheus", "parse_prometheus", "render_parsed",
           "DeltaRates", "MetricsServer"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _pname(name):
    """Registry name -> Prometheus metric name (dots become
    underscores; leading digits cannot occur in our registry)."""
    return _NAME_RE.sub("_", name)


def _fnum(v):
    """Float formatting matching Prometheus conventions: integral
    values render bare, +inf as ``+Inf``."""
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


# canonical label-value escaping lives in metrics (this module depends
# on it; the reverse import would cycle) — an operator-chosen
# replica_id must never produce an exposition parse_prometheus rejects
_esc_label = _metrics._esc_label_value


def _unesc_label(v):
    return v.replace("\\n", "\n").replace('\\"', '"') \
        .replace("\\\\", "\\")


def _labelblock(labels, **extra):
    """``{k="v",...}`` block for a sample line (sorted-key canonical;
    empty string when there are no labels at all). Values are escaped;
    the parser unescapes — key canonicalization therefore happens on
    the ESCAPED form on both sides, so render/parse keys agree.
    Delegates to ``metrics._label_body`` so registry keys
    (``metrics.label_key``) and rendered sample lines share one
    implementation."""
    items = {**(labels or {}), **extra}
    if not items:
        return ""
    return "{" + _metrics._label_body(items) + "}"


def _identity_lines(labels=None):
    """The ``replica_info`` gauge: value 1, identity as labels — the
    OpenMetrics idiom (cf. Prometheus ``target_info``) for stamping
    WHO produced a scrape without relabeling every series. Caller
    labels win on collision (a renamed replica stays consistent with
    its other series)."""
    ident = _metrics.replica_identity()
    merged = {k: ident[k] for k in
              ("replica_id", "host", "pid", "start_ts")}
    merged.update(labels or {})
    return ["# TYPE replica_info gauge",
            f"replica_info{_labelblock(merged)} 1"]


def render_prometheus(prefix=None, labels=None):
    """OpenMetrics text for every registered metric (optionally one
    ``prefix`` family). ``labels`` (a flat str dict) is stamped onto
    EVERY sample line — the fleet aggregator uses it to render
    per-replica series; the plain per-process exposition stays
    unlabeled for back-compat. Full (un-prefixed) renders append the
    ``replica_info`` identity gauge. Ends with ``# EOF`` per the
    spec."""
    with _metrics.registry._lock:
        items = sorted(_metrics.registry._metrics.items())
    lines, typed = [], set()
    lb = _labelblock(labels)
    for name, m in items:
        if prefix is not None and not name.startswith(prefix):
            continue
        # labeled instruments (per-slice KV gauges, metrics.label_key
        # registry keys) render their own labels MERGED with the
        # caller's stamp — the stamp wins on collision, matching the
        # replica_info precedence
        own = getattr(m, "labels", None)
        mlb = _labelblock({**own, **(labels or {})}) if own else lb
        pn = _pname(m.name)
        # TYPE once per family: labeled slices of one gauge share a
        # base name across registry keys, and OpenMetrics rejects
        # repeated metric-family metadata
        typeline = pn not in typed
        typed.add(pn)
        if isinstance(m, _metrics.Counter):
            if typeline:
                lines.append(f"# TYPE {pn} counter")
            lines.append(f"{pn}_total{mlb} {_fnum(m.value)}")
        elif isinstance(m, _metrics.Gauge):
            if typeline:
                lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn}{mlb} {_fnum(m.value)}")
        elif isinstance(m, _metrics.Histogram):
            snap = m._snap()
            if typeline:
                lines.append(f"# TYPE {pn} histogram")
            cum = 0
            bounds = [*m.bounds, float("inf")]
            blabels = [*map(str, m.bounds), "+inf"]
            for b, label in zip(bounds, blabels):
                cum += snap["buckets"][label]
                bb = _labelblock(labels, le=_fnum(b))
                line = f"{pn}_bucket{bb} {cum}"
                ex = snap["exemplars"].get(label)
                if ex is not None:
                    line += (f' # {{trace_id="{ex["trace_id"]}"}} '
                             f'{_fnum(ex["value"])} {ex["ts"]:.3f}')
                lines.append(line)
            lines.append(f"{pn}_sum{lb} {_fnum(snap['sum'])}")
            lines.append(f"{pn}_count{lb} {snap['count']}")
    if prefix is None:
        lines.extend(_identity_lines(labels))
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?'
    r'\s+(?P<value>[^ #]+)'
    r'(?:\s*#\s*\{(?P<exlabels>[^}]*)\}\s*(?P<exvalue>\S+)'
    r'(?:\s+(?P<exts>\S+))?)?\s*$')


def _labels(s):
    """Parse a label block body. Values unescape the render-side
    escapes (quote/backslash/newline); pathological values containing
    bare ``,``/``}`` are beyond this parser — keep label values to
    identifier-ish strings (replica ids, trace ids)."""
    out = {}
    for part in (s or "").split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            out[k.strip()] = _unesc_label(v.strip().strip('"'))
    return out


def parse_prometheus(text):
    """Parse an exposition back into plain data::

        {key: {"type": ..., "name": base_name, "value": ...}}       scalars
        {key: {"type": "histogram", "name": base_name,
               "buckets": {le: cum}, "sum": ..., "count": ...,
               "exemplars": {le: {"trace_id", "value"}}}}

    ``key`` is the base metric name for unlabeled series (back-compat:
    everything the per-process /metrics serves), or
    ``name{k="v",...}`` (sorted-key canonical, ``le`` excluded) for
    labeled series — the fleet aggregator's per-replica federation —
    whose entries additionally carry the ``labels`` dict. Counter
    ``_total`` / histogram series suffixes fold back onto the base
    name. Raises ValueError on a malformed sample line — this is the
    round-trip check, so garbage must not parse silently."""
    out = {}

    def base(name, kind, labels):
        key = name + _labelblock(labels) if labels else name
        e = out.setdefault(key, {"type": kind, "name": name}
                           if kind != "histogram"
                           else {"type": kind, "name": name,
                                 "buckets": {}, "sum": None,
                                 "count": None, "exemplars": {}})
        if labels:
            e["labels"] = dict(labels)
        return e

    types = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable sample line: {line!r}")
        name, value = m.group("name"), float(m.group("value"))
        labels = _labels(m.group("labels"))
        le = labels.pop("le", None)
        for suffix, field in (("_bucket", "buckets"), ("_sum", "sum"),
                              ("_count", "count")):
            stem = name[:-len(suffix)] if name.endswith(suffix) else None
            if stem and types.get(stem) == "histogram":
                h = base(stem, "histogram", labels)
                if field == "buckets":
                    h["buckets"][le] = value
                    if m.group("exvalue") is not None:
                        h["exemplars"][le] = {
                            **_labels(m.group("exlabels")),
                            "value": float(m.group("exvalue"))}
                else:
                    h[field] = value
                break
        else:
            if name.endswith("_total") and \
                    types.get(name[:-len("_total")]) == "counter":
                base(name[:-len("_total")], "counter",
                     labels)["value"] = value
            else:
                base(name, types.get(name, "gauge"),
                     labels)["value"] = value
    return out


# canonical implementation lives beside the bucket-percentile math in
# profiler.metrics (the Window needs both; metrics can't import us)
_le_sort_key = _metrics._le_sort_key


def render_parsed(parsed):
    """Inverse of :func:`parse_prometheus`: plain parsed/merged data
    back to OpenMetrics text. This is how the fleet aggregator serves
    ``/fleet/metrics`` — per-replica labeled series and unlabeled
    fleet aggregates in one exposition that parse_prometheus
    round-trips (exemplars included; their wall-clock ``ts`` is not
    retained by the parser, so a re-render omits it — the OpenMetrics
    timestamp is optional)."""
    lines, typed = [], set()
    for key in sorted(parsed):
        e = parsed[key]
        name = e.get("name") or key
        kind = e.get("type", "gauge")
        labels = e.get("labels")
        lb = _labelblock(labels)
        if name not in typed:
            lines.append(f"# TYPE {name} {kind}")
            typed.add(name)
        if kind == "counter":
            lines.append(f"{name}_total{lb} {_fnum(e['value'])}")
        elif kind == "histogram":
            for le in sorted(e.get("buckets") or {}, key=_le_sort_key):
                bb = _labelblock(labels, le=le)
                line = f"{name}_bucket{bb} {_fnum(e['buckets'][le])}"
                ex = (e.get("exemplars") or {}).get(le)
                if ex is not None and ex.get("trace_id"):
                    line += (f' # {{trace_id="{ex["trace_id"]}"}} '
                             f'{_fnum(ex["value"])}')
                lines.append(line)
            if e.get("sum") is not None:
                lines.append(f"{name}_sum{lb} {_fnum(e['sum'])}")
            if e.get("count") is not None:
                lines.append(f"{name}_count{lb} {_fnum(e['count'])}")
        else:
            lines.append(f"{name}{lb} {_fnum(e['value'])}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class DeltaRates:
    """Per-second rates between successive ``rates()`` calls: counters
    and histogram counts/sums (and, with ``include_buckets=True``,
    per-bucket counts as ``name.le.<bound>`` — what the burn-rate alert
    rules consume) diffed against the previous snapshot. First call
    primes the baseline and returns {}.

    Monotone series (counters, histogram counts/sums/buckets) clamp
    negative deltas to zero: a fresh process scraping the same endpoint
    — or a ``metrics.reset()`` between benchmark runs — resets the
    underlying counter, and a counter reset must read as "no events
    yet", never as a negative rate. Gauge deltas keep their sign (a
    shrinking queue IS a negative derivative, and the queue-growth
    alert rule relies on it)."""

    def __init__(self, prefix=None, include_buckets=False):
        self.prefix = prefix
        self.include_buckets = include_buckets
        self._prev = None
        self._prev_t = None
        self._lock = threading.Lock()

    def _flatten(self, snap):
        """(flat values, set of monotone names)."""
        flat, mono = {}, set()
        kinds = _metrics.registry.kinds(self.prefix)
        for name, v in snap.items():
            if isinstance(v, dict):
                flat[name + ".count"] = v["count"]
                flat[name + ".sum"] = v["sum"]
                mono.add(name + ".count")
                mono.add(name + ".sum")
                if self.include_buckets:
                    for label, c in (v.get("buckets") or {}).items():
                        key = f"{name}.le.{label}"
                        flat[key] = c
                        mono.add(key)
            else:
                flat[name] = v
                if kinds.get(name) is _metrics.Counter:
                    mono.add(name)
        return flat, mono

    def rates(self):
        now = time.monotonic()
        cur, mono = self._flatten(_metrics.snapshot(self.prefix))
        with self._lock:
            prev, prev_t = self._prev, self._prev_t
            self._prev, self._prev_t = cur, now
        if prev is None:
            return {}
        dt = max(now - prev_t, 1e-9)
        out = {}
        for name, v in cur.items():
            if not isinstance(v, (int, float)):
                continue
            d = v - prev.get(name, 0)
            if name in mono and d < 0:
                d = 0  # counter reset (fresh process / metrics.reset)
            out[name] = d / dt
        return out


def _slo_health(extra=None):
    """/healthz body: liveness + the serving SLO gauges a router
    health-checks (queue depth, live slots, KV pressure) and the
    terminal counters whose first derivative is the alert."""
    snap = _metrics.snapshot("serving.")
    body = {"status": "ok", "ts": time.time(),
            "slo": {k: snap[k] for k in
                    ("serving.queue.depth", "serving.slots.running",
                     "serving.kv.utilization") if k in snap},
            "counters": {k: snap[k] for k in
                         ("serving.completed", "serving.timeout",
                          "serving.rejected", "serving.preempt",
                          "serving.errors") if k in snap}}
    if extra:
        try:
            body.update(extra() or {})
        except Exception as e:  # noqa: BLE001 — health must not 500
            body["status"] = "error"
            body["error"] = f"{type(e).__name__}: {e}"
    return body


class MetricsServer:
    """Threaded stdlib HTTP endpoint over the registry + trace ring.
    Binds at construction (``port=0``, the default, binds an EPHEMERAL
    port — read the actually-bound one from ``.port`` / ``.address`` /
    ``url()``; never hardcode ports in tests or router configs);
    ``close()`` stops it. ``health_extra`` is an optional zero-arg
    callable merged into /healthz (ServingEngine passes its
    engine-state view); ``alerts`` an optional
    :class:`~paddle_tpu.profiler.alerts.AlertManager` served from
    ``/alerts`` (each GET also nudges its rate-limited evaluation);
    ``ready`` an optional zero-arg callable returning the ``/readyz``
    body (must carry a boolean ``ready`` — ServingEngine passes its
    drain-lifecycle view, docs/SERVING.md). Without one, ``/readyz``
    reports ready (a bare metrics process is routable)."""

    def __init__(self, port=0, host="127.0.0.1", health_extra=None,
                 alerts=None, ready=None):
        import http.server

        server = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence per-request stderr
                pass

            def _send(self, code, body, ctype):
                data = body.encode() if isinstance(body, str) else body
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0].rstrip("/") or "/"
                    if path == "/metrics":
                        self._send(
                            200, render_prometheus(),
                            "application/openmetrics-text; version=1.0.0;"
                            " charset=utf-8")
                    elif path == "/metrics/delta":
                        self._send(200, json.dumps(server._delta.rates()),
                                   "application/json")
                    elif path == "/healthz":
                        body = _slo_health(server._health_extra)
                        code = 200 if body["status"] == "ok" else 503
                        self._send(code, json.dumps(body),
                                   "application/json")
                    elif path == "/readyz":
                        body = server._ready_body()
                        code = 200 if body.get("ready") else 503
                        self._send(code, json.dumps(body),
                                   "application/json")
                    elif path == "/alerts":
                        mgr = server._alerts
                        if mgr is None:
                            # same body shape as the attached branch —
                            # pollers index these keys unconditionally
                            body = {"attached": False, "active": [],
                                    "history": [], "rules": [],
                                    "window_s": None}
                        else:
                            mgr.maybe_evaluate()
                            body = {"attached": True, **mgr.as_dict()}
                        self._send(200, json.dumps(body),
                                   "application/json")
                    elif path == "/summary":
                        # the human view (scorecard section included)
                        # without a Python shell; lazy import — the
                        # profiler package imports this module
                        from . import summary_text
                        self._send(200, summary_text(),
                                   "text/plain; charset=utf-8")
                    elif path == "/traces":
                        self._send(200,
                                   json.dumps(_tracing.export_ring()),
                                   "application/json")
                    elif path.startswith("/traces/"):
                        tid = path[len("/traces/"):]
                        trace = _tracing.export_trace(tid)
                        if not trace["traceEvents"]:
                            self._send(404, json.dumps(
                                {"error": f"unknown trace {tid!r}"}),
                                "application/json")
                        else:
                            self._send(200, json.dumps(trace),
                                       "application/json")
                    else:
                        self._send(404, json.dumps(
                            {"error": f"no route {path!r}"}),
                            "application/json")
                except BrokenPipeError:  # scraper went away mid-write
                    pass

        self._health_extra = health_extra
        self._alerts = alerts
        self._ready = ready
        self._delta = DeltaRates()
        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      _Handler)
        self._httpd.daemon_threads = True
        # the ACTUALLY-BOUND address: with port=0 the kernel picks an
        # ephemeral port, so callers must read it back from here
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="paddle-tpu-metrics-http", daemon=True)
        self._thread.start()

    def _ready_body(self):
        """/readyz body: the attached lifecycle view, or standalone
        readiness when nothing is attached. Never raises — a broken
        view must read as NOT ready (a router should stop sending
        traffic, not get a 500)."""
        if self._ready is None:
            return {"ready": True, "state": "READY", "attached": False}
        try:
            return self._ready()
        except Exception as e:  # noqa: BLE001 — readiness must not 500
            return {"ready": False, "state": "ERROR",
                    "error": f"{type(e).__name__}: {e}"}

    @property
    def address(self):
        """``(host, port)`` as actually bound."""
        return (self.host, self.port)

    def url(self, path="/metrics"):
        return f"http://{self.host}:{self.port}{path}"

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
