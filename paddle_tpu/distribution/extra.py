"""Long-tail distribution families + transforms.

Reference: python/paddle/distribution/ (binomial.py, cauchy.py, chi2.py,
continuous_bernoulli.py, geometric.py, gumbel.py, independent.py,
lognormal.py, multivariate_normal.py, poisson.py, student_t.py,
lkj_cholesky.py, transform.py, transformed_distribution.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.dispatch import unwrap
from ..core.random import next_key
from ..core.tensor import Tensor

__all__ = [
    "Binomial", "Cauchy", "Chi2", "ContinuousBernoulli", "Geometric",
    "Gumbel", "Independent", "LKJCholesky", "LogNormal",
    "MultivariateNormal", "Poisson", "StudentT", "Transform",
    "AffineTransform", "ExpTransform", "PowerTransform",
    "SigmoidTransform", "TanhTransform", "ChainTransform",
    "TransformedDistribution",
]


def _t(x):
    return Tensor(x)


def _arr(x, dtype=jnp.float32):
    return jnp.asarray(unwrap(x), dtype)


def _lgamma(x):
    return jax.scipy.special.gammaln(x)


from . import Distribution, Normal  # noqa: E402  (shares the base class)


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = _arr(total_count)
        self.probs = _arr(probs)
        super().__init__(jnp.broadcast_shapes(self.total_count.shape,
                                              self.probs.shape))

    @property
    def mean(self):
        return _t(self.total_count * self.probs)

    @property
    def variance(self):
        return _t(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        n = jnp.broadcast_to(self.total_count, shape)
        p = jnp.broadcast_to(self.probs, shape)
        out = jax.random.binomial(next_key(), n, p, shape=shape)
        return _t(out.astype(jnp.float32))

    def log_prob(self, value):
        k = _arr(value)
        n, p = self.total_count, self.probs
        logc = _lgamma(n + 1) - _lgamma(k + 1) - _lgamma(n - k + 1)
        return _t(logc + k * jnp.log(p) + (n - k) * jnp.log1p(-p))

    def entropy(self):
        # gaussian-ish analytic surrogate is inexact; sum the pmf support
        # only for scalar small n, else use 0.5*log(2*pi*e*npq)
        npq = self.total_count * self.probs * (1 - self.probs)
        return _t(0.5 * jnp.log(2 * math.pi * math.e * npq))


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return _t(self.loc + self.scale *
                  jax.random.cauchy(next_key(), shape))

    def log_prob(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return _t(-jnp.log(math.pi * self.scale * (1 + z * z)))

    def cdf(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return _t(jnp.arctan(z) / math.pi + 0.5)

    def entropy(self):
        return _t(jnp.broadcast_to(jnp.log(4 * math.pi * self.scale),
                                   self.batch_shape))


class Chi2(Distribution):
    def __init__(self, df, name=None):
        self.df = _arr(df)
        super().__init__(self.df.shape)

    @property
    def mean(self):
        return _t(self.df)

    @property
    def variance(self):
        return _t(2 * self.df)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        g = jax.random.gamma(next_key(),
                             jnp.broadcast_to(self.df / 2, shape))
        return _t(2 * g)

    def log_prob(self, value):
        v = _arr(value)
        k2 = self.df / 2
        return _t((k2 - 1) * jnp.log(v) - v / 2 - k2 * math.log(2.0)
                  - _lgamma(k2))


class ContinuousBernoulli(Distribution):
    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _arr(probs)
        self._lims = lims
        super().__init__(self.probs.shape)

    def _log_norm(self):
        p = self.probs
        # C(p) = 2*atanh(1-2p)/(1-2p), with the p->1/2 limit of log(2)
        near = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(near, 0.25, p)
        c = 2 * jnp.arctanh(1 - 2 * safe) / (1 - 2 * safe)
        return jnp.where(near, jnp.log(2.0), jnp.log(jnp.abs(c)))

    def log_prob(self, value):
        v = _arr(value)
        p = self.probs
        return _t(v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
                  + self._log_norm())

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(next_key(), shape,
                               minval=1e-6, maxval=1 - 1e-6)
        p = jnp.broadcast_to(self.probs, shape)
        near = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(near, 0.25, p)
        # inverse cdf for p != 1/2
        icdf = (jnp.log1p(u * (2 * safe - 1) / (1 - safe))
                / (jnp.log(safe) - jnp.log1p(-safe)))
        return _t(jnp.where(near, u, icdf))


class Geometric(Distribution):
    """Trials-until-first-success on support {0, 1, 2, ...} (reference
    geometric.py)."""

    def __init__(self, probs, name=None):
        self.probs = _arr(probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return _t((1 - self.probs) / self.probs)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(next_key(), shape, minval=1e-9,
                               maxval=1.0)
        return _t(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs)))

    def log_prob(self, value):
        k = _arr(value)
        return _t(k * jnp.log1p(-self.probs) + jnp.log(self.probs))

    def entropy(self):
        p = self.probs
        return _t(-((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return _t(self.loc + self.scale * np_euler)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return _t(self.loc + self.scale *
                  jax.random.gumbel(next_key(), shape))

    def log_prob(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return _t(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return _t(jnp.broadcast_to(jnp.log(self.scale) + 1 + np_euler,
                                   self.batch_shape))


np_euler = 0.5772156649015329


class Independent(Distribution):
    """Reinterprets trailing batch dims as event dims (reference
    independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = reinterpreted_batch_rank
        bs = base.batch_shape
        super().__init__(bs[:len(bs) - self.rank],
                         bs[len(bs) - self.rank:] + base.event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = unwrap(self.base.log_prob(value))
        return _t(jnp.sum(lp, axis=tuple(range(-self.rank, 0))))

    def entropy(self):
        e = unwrap(self.base.entropy())
        return _t(jnp.sum(e, axis=tuple(range(-self.rank, 0))))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return _t(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return _t((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return _t(jnp.exp(self.loc + self.scale *
                          jax.random.normal(next_key(), shape)))

    def log_prob(self, value):
        v = _arr(value)
        logv = jnp.log(v)
        return _t(-((logv - self.loc) ** 2) / (2 * self.scale ** 2)
                  - logv - jnp.log(self.scale)
                  - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return _t(self.loc + 0.5 + 0.5 * jnp.log(
            2 * math.pi * self.scale ** 2))


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, scale_tril=None,
                 name=None):
        self.loc = _arr(loc)
        if scale_tril is not None:
            self.scale_tril = _arr(scale_tril)
        else:
            self.scale_tril = jnp.linalg.cholesky(_arr(covariance_matrix))
        d = self.loc.shape[-1]
        super().__init__(self.loc.shape[:-1], (d,))

    @property
    def mean(self):
        return _t(self.loc)

    @property
    def covariance_matrix(self):
        return _t(self.scale_tril @ jnp.swapaxes(self.scale_tril, -1, -2))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape + self.event_shape
        eps = jax.random.normal(next_key(), shape)
        return _t(self.loc + jnp.einsum("...ij,...j->...i",
                                        self.scale_tril, eps))

    def log_prob(self, value):
        d = self.event_shape[0]
        diff = _arr(value) - self.loc
        sol = jax.scipy.linalg.solve_triangular(
            self.scale_tril, diff[..., None], lower=True)[..., 0]
        maha = jnp.sum(sol ** 2, -1)
        logdet = jnp.sum(jnp.log(jnp.diagonal(
            self.scale_tril, axis1=-2, axis2=-1)), -1)
        return _t(-0.5 * (maha + d * math.log(2 * math.pi)) - logdet)

    def entropy(self):
        d = self.event_shape[0]
        logdet = jnp.sum(jnp.log(jnp.diagonal(
            self.scale_tril, axis1=-2, axis2=-1)), -1)
        return _t(0.5 * d * (1 + math.log(2 * math.pi)) + logdet)


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return _t(self.rate)

    @property
    def variance(self):
        return _t(self.rate)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        out = jax.random.poisson(next_key(),
                                 jnp.broadcast_to(self.rate, shape))
        return _t(out.astype(jnp.float32))

    def log_prob(self, value):
        k = _arr(value)
        return _t(k * jnp.log(self.rate) - self.rate - _lgamma(k + 1))


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _arr(df)
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        t = jax.random.t(next_key(), jnp.broadcast_to(self.df, shape),
                         shape)
        return _t(self.loc + self.scale * t)

    def log_prob(self, value):
        z = (_arr(value) - self.loc) / self.scale
        nu = self.df
        return _t(_lgamma((nu + 1) / 2) - _lgamma(nu / 2)
                  - 0.5 * jnp.log(nu * math.pi) - jnp.log(self.scale)
                  - (nu + 1) / 2 * jnp.log1p(z * z / nu))


class LKJCholesky(Distribution):
    """Cholesky factors of LKJ-distributed correlation matrices
    (reference lkj_cholesky.py; onion-method sampler)."""

    def __init__(self, dim, concentration=1.0, name=None):
        self.dim = int(dim)
        self.concentration = _arr(concentration)
        super().__init__(self.concentration.shape,
                         (self.dim, self.dim))

    def sample(self, shape=()):
        d = self.dim
        eta = float(jnp.reshape(self.concentration, (-1,))[0])
        shape = tuple(shape)
        key = next_key()
        # onion method: build the cholesky row by row
        L = jnp.zeros(shape + (d, d), jnp.float32)
        L = L.at[..., 0, 0].set(1.0)
        beta = eta + (d - 2) / 2
        for i in range(1, d):
            key, k1, k2 = jax.random.split(key, 3)
            y = jax.random.beta(k1, jnp.float32(i / 2), jnp.float32(beta),
                                shape, dtype=jnp.float32)
            beta = beta - 0.5
            u = jax.random.normal(k2, shape + (i,), jnp.float32)
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            w = jnp.sqrt(y)[..., None] * u
            L = L.at[..., i, :i].set(w)
            L = L.at[..., i, i].set(jnp.sqrt(jnp.maximum(1 - y, 1e-12)))
        return _t(L)

    def log_prob(self, value):
        L = _arr(value)
        d = self.dim
        eta = self.concentration
        diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
        orders = jnp.arange(d - 1, 0, -1, dtype=jnp.float32)
        unnorm = jnp.sum((2 * (eta[..., None] - 1) + d - 1 - orders)
                         * jnp.log(diag), -1)
        # normalization (reference lkj_cholesky.py closed form)
        alpha = eta + (d - 1) / 2.0
        k = jnp.arange(1, d, dtype=jnp.float32)
        norm = jnp.sum(
            0.5 * k * math.log(math.pi)
            + _lgamma(alpha - k / 2.0) - _lgamma(alpha), -1)
        return _t(unnorm - norm)


# ---------------------------------------------------------------------------
# transforms (reference transform.py) + TransformedDistribution
# ---------------------------------------------------------------------------

class Transform:
    def forward(self, x):
        return _t(self._fwd(_arr(x)))

    def inverse(self, y):
        return _t(self._inv(_arr(y)))

    def forward_log_det_jacobian(self, x):
        return _t(self._fldj(_arr(x)))

    def inverse_log_det_jacobian(self, y):
        return _t(-self._fldj(self._inv(_arr(y))))


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def _fwd(self, x):
        return self.loc + self.scale * x

    def _inv(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _fwd(self, x):
        return jnp.exp(x)

    def _inv(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _arr(power)

    def _fwd(self, x):
        return jnp.power(x, self.power)

    def _inv(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _fwd(self, x):
        return jax.nn.sigmoid(x)

    def _inv(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _fwd(self, x):
        return jnp.tanh(x)

    def _inv(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _fwd(self, x):
        for t in self.transforms:
            x = t._fwd(x)
        return x

    def _inv(self, y):
        for t in reversed(self.transforms):
            y = t._inv(y)
        return y

    def _fldj(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._fldj(x)
            x = t._fwd(x)
        return total


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transform = ChainTransform(transforms) \
            if len(transforms) > 1 else transforms[0]
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = unwrap(self.base.sample(shape))
        return _t(self.transform._fwd(x))

    def log_prob(self, value):
        y = _arr(value)
        x = self.transform._inv(y)
        base_lp = unwrap(self.base.log_prob(_t(x)))
        return _t(base_lp - self.transform._fldj(x))
