"""`paddle.distribution` (reference: python/paddle/distribution/)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import unwrap
from ..core.random import next_key
from ..core.tensor import Tensor

__all__ = ["Distribution", "ExponentialFamily", "Normal", "Uniform",
           "Categorical", "Bernoulli",
           "Beta", "Dirichlet", "Exponential", "Gamma", "Laplace",
           "Multinomial", "kl_divergence", "register_kl",
           # long tail (distribution/extra.py)
           "Binomial", "Cauchy", "Chi2", "ContinuousBernoulli",
           "Geometric", "Gumbel", "Independent", "LKJCholesky",
           "LogNormal", "MultivariateNormal", "Poisson", "StudentT",
           "Transform", "AffineTransform", "ExpTransform",
           "PowerTransform", "SigmoidTransform", "TanhTransform",
           "ChainTransform", "TransformedDistribution"]


def _t(x):
    return Tensor(x)


def _arr(x, dtype=jnp.float32):
    return jnp.asarray(unwrap(x), dtype)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _t(jnp.exp(unwrap(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class ExponentialFamily(Distribution):
    """Base for distributions of the exponential family (reference
    python/paddle/distribution/exponential_family.py): entropy is derived
    from the log-normalizer via the Bregman-divergence identity
    H = F(θ) - <θ, ∇F(θ)> - E[carrier], with ∇F from jax.grad instead of
    the reference's double-backward graph."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        nparams = [jnp.asarray(unwrap(p), jnp.float32)
                   for p in self._natural_parameters]

        def log_norm_sum(*ps):
            return jnp.sum(self._log_normalizer(*ps))

        grads = jax.grad(log_norm_sum, argnums=tuple(range(len(nparams))))(
            *nparams)
        ent = -self._mean_carrier_measure
        ent = ent + self._log_normalizer(*nparams)
        for p, g in zip(nparams, grads):
            ent = ent - p * g
        return _t(ent)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return _t(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _t(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        eps = jax.random.normal(next_key(), shape)
        return _t(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _arr(value)
        var = self.scale ** 2
        return _t(-((v - self.loc) ** 2) / (2 * var) -
                  jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return _t(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self.batch_shape))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(next_key(), shape)
        return _t(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        return _t(jnp.where(inside, -jnp.log(self.high - self.low),
                            -jnp.inf))

    def entropy(self):
        return _t(jnp.log(self.high - self.low))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None:
            arr = _arr(logits)
            # paddle semantics: `logits` are unnormalized probs
            self.probs_arr = arr / jnp.sum(arr, -1, keepdims=True) \
                if jnp.all(arr >= 0) else jax.nn.softmax(arr, -1)
        else:
            p = _arr(probs)
            self.probs_arr = p / jnp.sum(p, -1, keepdims=True)
        super().__init__(self.probs_arr.shape[:-1])

    @property
    def probs(self):
        return _t(self.probs_arr)

    def sample(self, shape=()):
        logits = jnp.log(jnp.maximum(self.probs_arr, 1e-38))
        return _t(jax.random.categorical(
            next_key(), logits, shape=tuple(shape) + self.batch_shape))

    def log_prob(self, value):
        idx = _arr(value, jnp.int32)
        return _t(jnp.log(jnp.take_along_axis(
            self.probs_arr, idx[..., None], -1)[..., 0]))

    def entropy(self):
        p = self.probs_arr
        return _t(-jnp.sum(p * jnp.log(jnp.maximum(p, 1e-38)), -1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_arr = _arr(probs)
        super().__init__(self.probs_arr.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return _t(jax.random.bernoulli(
            next_key(), self.probs_arr, shape).astype(jnp.float32))

    def log_prob(self, value):
        v = _arr(value)
        p = self.probs_arr
        return _t(v * jnp.log(jnp.maximum(p, 1e-38)) +
                  (1 - v) * jnp.log(jnp.maximum(1 - p, 1e-38)))

    def entropy(self):
        p = self.probs_arr
        return _t(-(p * jnp.log(jnp.maximum(p, 1e-38)) +
                    (1 - p) * jnp.log(jnp.maximum(1 - p, 1e-38))))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return _t(jax.random.beta(next_key(), self.alpha, self.beta,
                                  shape))

    def log_prob(self, value):
        from jax.scipy.special import betaln
        v = _arr(value)
        return _t((self.alpha - 1) * jnp.log(v) +
                  (self.beta - 1) * jnp.log1p(-v) -
                  betaln(self.alpha, self.beta))

    @property
    def mean(self):
        return _t(self.alpha / (self.alpha + self.beta))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _arr(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        return _t(jax.random.dirichlet(
            next_key(), self.concentration,
            tuple(shape) + self.batch_shape))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _arr(value)
        a = self.concentration
        return _t(jnp.sum((a - 1) * jnp.log(v), -1) +
                  gammaln(jnp.sum(a, -1)) - jnp.sum(gammaln(a), -1))


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return _t(jax.random.exponential(next_key(), shape) / self.rate)

    def log_prob(self, value):
        v = _arr(value)
        return _t(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return _t(1.0 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = _arr(concentration)
        self.rate = _arr(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return _t(jax.random.gamma(next_key(), self.concentration, shape) /
                  self.rate)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _arr(value)
        a, b = self.concentration, self.rate
        return _t(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v -
                  gammaln(a))


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return _t(self.loc + self.scale *
                  jax.random.laplace(next_key(), shape))

    def log_prob(self, value):
        v = _arr(value)
        return _t(-jnp.abs(v - self.loc) / self.scale -
                  jnp.log(2 * self.scale))

    def entropy(self):
        return _t(1 + jnp.log(2 * self.scale))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        p = _arr(probs)
        self.probs_arr = p / jnp.sum(p, -1, keepdims=True)
        super().__init__(self.probs_arr.shape[:-1],
                         self.probs_arr.shape[-1:])

    def sample(self, shape=()):
        cat = jax.random.categorical(
            next_key(), jnp.log(jnp.maximum(self.probs_arr, 1e-38)),
            shape=tuple(shape) + (self.total_count,) + self.batch_shape)
        k = self.probs_arr.shape[-1]
        onehot = jax.nn.one_hot(cat, k)
        return _t(jnp.sum(onehot, axis=len(shape)))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _arr(value)
        logp = jnp.log(jnp.maximum(self.probs_arr, 1e-38))
        coeff = gammaln(jnp.asarray(self.total_count + 1.0)) - \
            jnp.sum(gammaln(v + 1.0), -1)
        return _t(coeff + jnp.sum(v * logp, -1))


_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a pairwise KL implementation (reference
    python/paddle/distribution/kl.py register_kl): the most specific
    registered (type(p), type(q)) pair by MRO distance is dispatched."""

    def decorator(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return decorator


def kl_divergence(p, q):
    best, best_fn = None, None
    for (cp, cq), fn in _KL_REGISTRY.items():
        if isinstance(p, cp) and isinstance(q, cq):
            score = (type(p).__mro__.index(cp) +
                     type(q).__mro__.index(cq))
            if best is None or score < best:
                best, best_fn = score, fn
    if best_fn is None:
        raise NotImplementedError(
            f"kl_divergence({type(p).__name__}, {type(q).__name__})")
    return best_fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_p, var_q = p.scale ** 2, q.scale ** 2
    return _t(jnp.log(q.scale / p.scale) +
              (var_p + (p.loc - q.loc) ** 2) / (2 * var_q) - 0.5)


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    pp, qq = p.probs_arr, q.probs_arr
    return _t(jnp.sum(pp * (jnp.log(jnp.maximum(pp, 1e-38)) -
                            jnp.log(jnp.maximum(qq, 1e-38))), -1))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    return _t(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    a, b = p.probs_arr, q.probs_arr
    eps = 1e-38
    return _t(a * (jnp.log(jnp.maximum(a, eps)) -
                   jnp.log(jnp.maximum(b, eps))) +
              (1 - a) * (jnp.log(jnp.maximum(1 - a, eps)) -
                         jnp.log(jnp.maximum(1 - b, eps))))


from .extra import *  # noqa: F401,F403,E402
from . import transform  # noqa: F401,E402  (paddle.distribution.transform)
