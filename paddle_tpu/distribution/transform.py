"""`paddle.distribution.transform` submodule (reference
python/paddle/distribution/transform.py): the Transform classes are
defined in extra.py and re-exported from the package root; this module
mirrors the reference's import path."""

from .extra import (  # noqa: F401
    AffineTransform,
    ChainTransform,
    ExpTransform,
    PowerTransform,
    SigmoidTransform,
    TanhTransform,
    Transform,
)

__all__ = [
    "Transform", "AffineTransform", "ExpTransform", "PowerTransform",
    "SigmoidTransform", "TanhTransform", "ChainTransform",
]
