"""Audio functionals (reference: python/paddle/audio/functional/) —
windows, mel filterbanks, dct matrices; all pure jnp."""

from __future__ import annotations

import math

import jax.numpy as jnp

from ..core.dispatch import unwrap
from ..core.tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def hz_to_mel(freq, htk=False):
    if htk:
        return 2595.0 * math.log10(1.0 + freq / 700.0) \
            if isinstance(freq, (int, float)) else \
            2595.0 * jnp.log10(1.0 + freq / 700.0)
    # slaney
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (freq - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    if isinstance(freq, (int, float)):
        if freq >= min_log_hz:
            return min_log_mel + math.log(freq / min_log_hz) / logstep
        return mels
    return jnp.where(freq >= min_log_hz,
                     min_log_mel + jnp.log(freq / min_log_hz) / logstep,
                     mels)


def mel_to_hz(mel, htk=False):
    if htk:
        return 700.0 * (10.0 ** (mel / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(mel >= min_log_mel,
                     min_log_hz * jnp.exp(logstep * (mel - min_log_mel)),
                     freqs)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    low = hz_to_mel(f_min, htk)
    high = hz_to_mel(f_max, htk)
    mels = jnp.linspace(low, high, n_mels)
    return Tensor(mel_to_hz(mels, htk))


def fft_frequencies(sr, n_fft):
    return Tensor(jnp.linspace(0, sr / 2, n_fft // 2 + 1))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney"):
    f_max = f_max or sr / 2.0
    fft_freqs = jnp.linspace(0, sr / 2, n_fft // 2 + 1)
    mel_f = unwrap(mel_frequencies(n_mels + 2, f_min, f_max, htk))
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fft_freqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    return Tensor(weights)


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    s = unwrap(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return Tensor(log_spec)


def create_dct(n_mfcc, n_mels, norm="ortho"):
    n = jnp.arange(float(n_mels))
    k = jnp.arange(float(n_mfcc))[:, None]
    dct = jnp.cos(math.pi / n_mels * (n + 0.5) * k)
    if norm == "ortho":
        dct = dct.at[0].multiply(1.0 / math.sqrt(2))
        dct = dct * math.sqrt(2.0 / n_mels)
    return Tensor(dct.T)


def get_window(window, win_length, fftbins=True):
    n = win_length
    i = jnp.arange(n)
    denom = n if fftbins else n - 1
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * jnp.cos(2 * math.pi * i / denom)
    elif window == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * math.pi * i / denom)
    elif window == "blackman":
        w = (0.42 - 0.5 * jnp.cos(2 * math.pi * i / denom) +
             0.08 * jnp.cos(4 * math.pi * i / denom))
    elif window in ("rect", "boxcar", "rectangular"):
        w = jnp.ones(n)
    else:
        raise ValueError(f"unknown window {window!r}")
    return Tensor(w)
