"""`paddle.audio.backends` (reference audio/backends/wave_backend.py):
WAV load/save/info over the stdlib wave module."""

from __future__ import annotations

import wave as _wave

import numpy as np

from ..core.dispatch import unwrap
from ..core.tensor import Tensor

__all__ = ["AudioInfo", "info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend"]


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath):
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(),
                         f.getnchannels(), f.getsampwidth() * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Returns (waveform Tensor [channels, samples] when channels_first,
    sample_rate)."""
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        ch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    dtype = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dtype).reshape(-1, ch)
    if normalize:
        if width == 1:
            wav = (data.astype(np.float32) - 128.0) / 128.0
        else:
            wav = data.astype(np.float32) / float(2 ** (width * 8 - 1))
    else:
        wav = data.astype(np.float32)
    if channels_first:
        wav = wav.T
    return Tensor(np.ascontiguousarray(wav)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_S", bits_per_sample=16):
    wav = np.asarray(unwrap(src))
    if channels_first:
        wav = wav.T  # -> [samples, channels]
    if wav.ndim == 1:
        wav = wav[:, None]
    width = bits_per_sample // 8
    scale = float(2 ** (bits_per_sample - 1) - 1)
    data = np.clip(wav, -1.0, 1.0) * scale
    dtype = {2: np.int16, 4: np.int32}[width]
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(wav.shape[1])
        f.setsampwidth(width)
        f.setframerate(int(sample_rate))
        f.writeframes(data.astype(dtype).tobytes())


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name):
    if backend_name not in ("wave_backend",):
        raise NotImplementedError(
            f"backend {backend_name!r} unavailable; only the stdlib "
            "wave backend ships in this build")
