"""`paddle.audio` (reference: python/paddle/audio/ — features and
functional: Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC)."""

from . import functional  # noqa: F401
from . import features  # noqa: F401
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from .backends import info, load, save  # noqa: F401
