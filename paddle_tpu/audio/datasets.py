"""`paddle.audio.datasets` (reference audio/datasets/: TESS, ESC50).
Local-file parsers like the text/vision datasets (archives of wav files;
labels from filenames/metadata)."""

from __future__ import annotations

import csv
import os

import numpy as np

from ..io import Dataset
from . import backends

__all__ = ["TESS", "ESC50"]


class _AudioFeatureDataset(Dataset):
    def __init__(self, feat_type="raw", sample_rate=None, **feat_kwargs):
        self.feat_type = feat_type
        self.feat_kwargs = feat_kwargs

    def _featurize(self, wav, sr):
        if self.feat_type == "raw":
            return wav
        from .features import (LogMelSpectrogram, MFCC, MelSpectrogram,
                               Spectrogram)
        cls = {"spectrogram": Spectrogram,
               "melspectrogram": MelSpectrogram,
               "logmelspectrogram": LogMelSpectrogram,
               "mfcc": MFCC}[self.feat_type]
        ext = cls(sr=sr, **self.feat_kwargs) if "sr" in \
            cls.__init__.__code__.co_varnames else cls(**self.feat_kwargs)
        return ext(wav)


class TESS(_AudioFeatureDataset):
    """Toronto emotional speech set (reference tess.py): wav files named
    <talker>_<word>_<emotion>.wav under per-speaker folders."""

    EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps",
                "sad"]

    def __init__(self, data_dir=None, mode="train", n_folds=5,
                 split=1, feat_type="raw", archive=None, **kwargs):
        super().__init__(feat_type, **kwargs)
        assert data_dir, "pass data_dir= pointing at the extracted TESS"
        files = []
        for root, _, names in os.walk(data_dir):
            files += [os.path.join(root, n) for n in names
                      if n.lower().endswith(".wav")]
        files.sort()
        self.files = []
        self.labels = []
        for i, f in enumerate(files):
            emotion = os.path.splitext(os.path.basename(f))[0].split(
                "_")[-1].lower()
            if emotion not in self.EMOTIONS:
                continue
            fold = i % n_folds + 1
            keep = (fold != split) if mode == "train" else (fold == split)
            if keep:
                self.files.append(f)
                self.labels.append(self.EMOTIONS.index(emotion))

    def __getitem__(self, idx):
        wav, sr = backends.load(self.files[idx])
        return self._featurize(wav, sr), np.int64(self.labels[idx])

    def __len__(self):
        return len(self.files)


class ESC50(_AudioFeatureDataset):
    """ESC-50 environmental sounds (reference esc50.py): audio/ dir +
    meta/esc50.csv with filename,fold,target columns."""

    def __init__(self, data_dir=None, mode="train", split=1,
                 feat_type="raw", archive=None, **kwargs):
        super().__init__(feat_type, **kwargs)
        assert data_dir, "pass data_dir= pointing at the extracted ESC-50"
        meta = os.path.join(data_dir, "meta", "esc50.csv")
        audio_dir = os.path.join(data_dir, "audio")
        self.files = []
        self.labels = []
        with open(meta) as f:
            for row in csv.DictReader(f):
                fold = int(row["fold"])
                keep = (fold != split) if mode == "train" \
                    else (fold == split)
                if keep:
                    self.files.append(
                        os.path.join(audio_dir, row["filename"]))
                    self.labels.append(int(row["target"]))

    def __getitem__(self, idx):
        wav, sr = backends.load(self.files[idx])
        return self._featurize(wav, sr), np.int64(self.labels[idx])

    def __len__(self):
        return len(self.files)
