"""Audio feature layers (reference: python/paddle/audio/features/
layers.py: Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC)."""

from __future__ import annotations

import jax.numpy as jnp

from .. import nn
from ..core.dispatch import apply, unwrap
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(nn.Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = unwrap(AF.get_window(window, self.win_length))
        if self.win_length < n_fft:
            pad = (n_fft - self.win_length) // 2
            w = jnp.pad(w, (pad, n_fft - self.win_length - pad))
        self._window = w

    def forward(self, x):
        n_fft, hop = self.n_fft, self.hop_length
        win = self._window
        power = self.power
        center = self.center
        pad_mode = self.pad_mode

        def fn(a):
            if a.ndim == 1:
                a = a[None]
            if center:
                a = jnp.pad(a, ((0, 0), (n_fft // 2, n_fft // 2)),
                            mode=pad_mode)
            n_frames = 1 + (a.shape[-1] - n_fft) // hop
            idx = (jnp.arange(n_frames)[:, None] * hop +
                   jnp.arange(n_fft)[None, :])
            frames = a[:, idx] * win  # [b, frames, n_fft]
            spec = jnp.fft.rfft(frames, axis=-1)
            mag = jnp.abs(spec)
            if power != 1.0:
                mag = mag ** power
            return jnp.swapaxes(mag, 1, 2)  # [b, freq, frames]

        return apply(fn, x, name="spectrogram")


class MelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode)
        self._fbank = unwrap(AF.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm))

    def forward(self, x):
        spec = self.spectrogram(x)
        fb = self._fbank

        def fn(s):
            return jnp.einsum("mf,bft->bmt", fb, s)

        return apply(fn, spec, name="mel_spectrogram")


class LogMelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length,
                                  window, power, center, pad_mode, n_mels,
                                  f_min, f_max, htk, norm)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return AF.power_to_db(self.mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(nn.Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr, n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        n_mels, f_min, f_max, htk, norm,
                                        ref_value, amin, top_db)
        self._dct = unwrap(AF.create_dct(n_mfcc, n_mels))

    def forward(self, x):
        lm = self.logmel(x)
        dct = self._dct

        def fn(s):
            # dct: [n_mels, n_mfcc]
            return jnp.einsum("mk,bmt->bkt", dct, s)

        return apply(fn, lm, name="mfcc")
