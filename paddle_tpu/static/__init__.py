"""`paddle.static` compatibility surface.

The reference's legacy static-graph mode (python/paddle/static/: Program /
Executor / feed-fetch) has no TPU-native analogue — the compiled path is
`paddle_tpu.jit` (trace once, XLA executes). This module keeps the most-
used static entry points working by mapping them onto that path:
`InputSpec`/`data` declare signatures, `save/load_inference_model` persist
a network + params for the inference Predictor, and Executor/Program
raise with precise migration guidance instead of silently diverging.
"""

from __future__ import annotations

import numpy as np

from ..core import dtype as dtype_mod

__all__ = ["InputSpec", "data", "save_inference_model",
           "load_inference_model", "Program", "Executor",
           "default_main_program", "default_startup_program",
           "program_guard", "name_scope", "gradients"]


class InputSpec:
    """reference paddle.static.InputSpec (python/paddle/static/
    input.py)."""

    def __init__(self, shape, dtype="float32", name=None,
                 stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(list(ndarray.shape), str(ndarray.dtype), name)

    def batch(self, batch_size):
        return InputSpec([batch_size] + self.shape, self.dtype, self.name)

    def unbatch(self):
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Persist params of the layer owning ``fetch_vars`` for the
    Predictor. In the eager front end the common call form is
    save_inference_model(prefix, layer_or_specs, layer, ...)."""
    from ..framework.io import save
    layer = None
    for cand in (fetch_vars, executor, program):
        if hasattr(cand, "state_dict"):
            layer = cand
            break
    if layer is None:
        raise ValueError(
            "save_inference_model: pass the Layer as fetch_vars "
            "(TPU-native deployment serializes params + a network factory; "
            "see paddle_tpu.inference.Config)")
    save(layer.state_dict(), path_prefix + ".pdiparams")


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..framework.io import load
    return load(path_prefix + ".pdiparams")


_MIGRATION = (
    "the legacy static-graph Program/Executor does not exist in "
    "paddle_tpu: decorate your model/step with paddle_tpu.jit.to_static "
    "or use paddle_tpu.jit.TrainStep — the traced function IS the "
    "program, compiled and scheduled by XLA")


class Program:
    def __init__(self):
        raise NotImplementedError(_MIGRATION)


class Executor:
    def __init__(self, place=None):
        raise NotImplementedError(_MIGRATION)


def default_main_program():
    raise NotImplementedError(_MIGRATION)


def default_startup_program():
    raise NotImplementedError(_MIGRATION)


class program_guard:
    def __init__(self, *a, **k):
        raise NotImplementedError(_MIGRATION)


class name_scope:
    def __init__(self, name=""):
        self.name = name

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..core.autograd import grad
    return grad(targets, inputs, grad_outputs=target_gradients,
                retain_graph=True, allow_unused=True)
