"""`paddle.static` compatibility surface.

The reference's legacy static-graph mode (python/paddle/static/: Program /
Executor / feed-fetch) has no TPU-native analogue — the compiled path is
`paddle_tpu.jit` (trace once, XLA executes). This module keeps the most-
used static entry points working by mapping them onto that path:
`InputSpec`/`data` declare signatures, `save/load_inference_model` persist
a network + params for the inference Predictor, and Executor/Program
raise with precise migration guidance instead of silently diverging.
"""

from __future__ import annotations

import numpy as np

from ..core import dtype as dtype_mod

__all__ = ["InputSpec", "data", "save_inference_model", "accuracy",
           "auc", "cpu_places", "cuda_places", "create_parameter",
           "create_global_var", "device_guard", "global_scope", "Print",
           "Variable", "WeightNormParamAttr", "ExponentialMovingAverage",
           "BuildStrategy", "CompiledProgram", "IpuStrategy",
           "IpuCompiledProgram", "append_backward", "serialize_program",
           "deserialize_program", "serialize_persistables",
           "deserialize_persistables", "ctr_metric_bundle", "save", "load",
           "save_to_file", "load_from_file", "load_program_state",
           "set_program_state", "normalize_program", "scope_guard",
           "py_func", "xpu_places", "ipu_shard_guard", "set_ipu_shard",
           "load_inference_model", "Program", "Executor",
           "default_main_program", "default_startup_program",
           "program_guard", "name_scope", "gradients"]


class InputSpec:
    """reference paddle.static.InputSpec (python/paddle/static/
    input.py)."""

    def __init__(self, shape, dtype="float32", name=None,
                 stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(list(ndarray.shape), str(ndarray.dtype), name)

    def batch(self, batch_size):
        return InputSpec([batch_size] + self.shape, self.dtype, self.name)

    def unbatch(self):
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Persist params of the layer owning ``fetch_vars`` for the
    Predictor. In the eager front end the common call form is
    save_inference_model(prefix, layer_or_specs, layer, ...)."""
    from ..framework.io import save
    layer = None
    for cand in (fetch_vars, executor, program):
        if hasattr(cand, "state_dict"):
            layer = cand
            break
    if layer is None:
        raise ValueError(
            "save_inference_model: pass the Layer as fetch_vars "
            "(TPU-native deployment serializes params + a network factory; "
            "see paddle_tpu.inference.Config)")
    save(layer.state_dict(), path_prefix + ".pdiparams")


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..framework.io import load
    return load(path_prefix + ".pdiparams")


_MIGRATION = (
    "the legacy static-graph Program/Executor does not exist in "
    "paddle_tpu: decorate your model/step with paddle_tpu.jit.to_static "
    "or use paddle_tpu.jit.TrainStep — the traced function IS the "
    "program, compiled and scheduled by XLA")


class Program:
    def __init__(self):
        raise NotImplementedError(_MIGRATION)


class Executor:
    def __init__(self, place=None):
        raise NotImplementedError(_MIGRATION)


def default_main_program():
    raise NotImplementedError(_MIGRATION)


def default_startup_program():
    raise NotImplementedError(_MIGRATION)


class program_guard:
    def __init__(self, *a, **k):
        raise NotImplementedError(_MIGRATION)


class name_scope:
    def __init__(self, name=""):
        self.name = name

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..core.autograd import grad
    return grad(targets, inputs, grad_outputs=target_gradients,
                retain_graph=True, allow_unused=True)


# -- runnable pieces of the static surface ------------------------------

def accuracy(input, label, k=1, correct=None, total=None, name=None):
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    from ..metric import Auc
    m = Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(input, label)
    from ..core.tensor import Tensor
    import numpy as np
    return Tensor(np.float32(m.accumulate()))


def cpu_places(device_count=None):
    from ..core.place import Place
    n = device_count or 1
    return [Place("cpu", i) for i in range(n)]


def cuda_places(device_ids=None):
    """Accelerator places (the accelerator here is the TPU)."""
    import jax

    from ..core.place import Place
    ids = device_ids if device_ids is not None else \
        range(jax.device_count())
    return [Place("tpu", i) for i in ids]


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..compat_toplevel import create_parameter as _cp
    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    import jax.numpy as jnp

    from ..core.dtype import convert_dtype
    from ..core.tensor import Tensor
    t = Tensor(jnp.full(shape, value, convert_dtype(dtype)))
    t.persistable = persistable
    if name:
        t.name = name
    return t


class device_guard:
    """Reference static.device_guard: context pinning ops to a device.
    XLA owns placement; accepted and recorded for compatibility."""

    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def global_scope():
    class _Scope:
        def var(self, name):
            return None

        def find_var(self, name):
            return None
    return _Scope()


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Reference static.Print: identity that logs the value."""
    msg = message or "Print"
    print(f"{msg}: shape={list(input.shape)} dtype={input.dtype}")
    print(input.numpy() if hasattr(input, "numpy") else input)
    return input


from ..core.tensor import Tensor as Variable  # noqa: E402,F401


class WeightNormParamAttr:
    """Reference WeightNormParamAttr: weight-normalized parameter config
    (paddle_tpu applies weight norm through nn.utils-style reparam at
    layer level)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer


class ExponentialMovingAverage:
    """Reference static.ExponentialMovingAverage, eager-native: tracks
    EMA shadows of every trainable parameter; apply()/restore() swap them
    in and out (the evaluation pattern)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self.decay = decay
        self._shadow = {}
        self._backup = {}
        self._params = []
        self._step = 0

    def register(self, parameters):
        import jax.numpy as jnp
        self._params = [p for p in parameters if not p.stop_gradient]
        for p in self._params:
            self._shadow[id(p)] = p._data.astype(jnp.float32)

    def update(self, parameters=None):
        import jax.numpy as jnp
        if parameters is not None and not self._params:
            self.register(parameters)
        self._step += 1
        d = min(self.decay, (1 + self._step) / (10 + self._step))
        for p in self._params:
            old = self._shadow[id(p)]
            self._shadow[id(p)] = d * old + (1 - d) * p._data.astype(
                jnp.float32)

    def apply(self, executor=None, need_restore=True):
        ema = self

        class _Guard:
            def __enter__(self_g):
                for p in ema._params:
                    ema._backup[id(p)] = p._data
                    p._rebind(ema._shadow[id(p)].astype(p._data.dtype))
                return self_g

            def __exit__(self_g, *exc):
                if need_restore:
                    ema.restore()
                return False
        return _Guard()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._rebind(self._backup.pop(id(p)))


class BuildStrategy:
    """Accepted-knob container (reference BuildStrategy; XLA owns
    scheduling/fusion)."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()


class IpuStrategy:
    def __init__(self):
        raise NotImplementedError("IPU is not a target of this build")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU is not a target of this build")


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    raise NotImplementedError(
        "static program autodiff does not exist here; call "
        "loss.backward() (eager tape) or build a TrainStep (compiled)")


def serialize_program(feed_vars, fetch_vars, **kwargs):
    raise NotImplementedError(
        "ProgramDesc serialization n/a; use paddle_tpu.jit.save or "
        "onnx.export_stablehlo")


def deserialize_program(data):
    raise NotImplementedError(
        "ProgramDesc serialization n/a; use paddle_tpu.jit.load")


def serialize_persistables(feed_vars, fetch_vars, **kwargs):
    raise NotImplementedError("use paddle_tpu.save")


def deserialize_persistables(program, data, executor):
    raise NotImplementedError("use paddle_tpu.load")


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """CTR eval bundle (reference ctr_metric_bundle): returns sqrerr,
    abserr, prob, q, pos, total as tensors."""
    import jax.numpy as jnp

    from ..core.dispatch import unwrap
    from ..core.tensor import Tensor
    pred = unwrap(input).reshape(-1).astype(jnp.float32)
    lab = unwrap(label).reshape(-1).astype(jnp.float32)
    sqrerr = jnp.sum((pred - lab) ** 2)
    abserr = jnp.sum(jnp.abs(pred - lab))
    prob = jnp.sum(pred)
    q = jnp.sum(pred)
    pos = jnp.sum(lab)
    total = jnp.asarray(pred.shape[0], jnp.float32)
    return tuple(Tensor(v) for v in
                 (sqrerr, abserr, prob, q, pos, total))


def save(program, model_path, protocol=4, **configs):
    raise NotImplementedError("static programs n/a; use paddle_tpu.save")


def load(program, model_path, executor=None, var_list=None):
    raise NotImplementedError("static programs n/a; use paddle_tpu.load")


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content if isinstance(content, bytes)
                else bytes(content))


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def load_program_state(model_path, var_list=None):
    """Load a paddle_tpu.save checkpoint as a flat numpy state dict."""
    import numpy as np

    from ..framework.io import load as _load
    state = _load(model_path)

    def to_np(tree, prefix=""):
        out = {}
        for k, v in tree.items():
            key = f"{prefix}{k}"
            if isinstance(v, dict):
                out.update(to_np(v, key + "."))
            elif hasattr(v, "numpy"):
                out[key] = np.asarray(v.numpy())
            else:
                out[key] = v
        return out
    return to_np(state) if isinstance(state, dict) else state


def set_program_state(program, state):
    raise NotImplementedError(
        "static programs n/a; call layer.set_state_dict(state)")


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    raise NotImplementedError("static programs n/a")


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference static.py_func: in eager-first design python functions
    run directly; apply func and return its output."""
    result = func(x)
    return result if result is not None else out


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


class ipu_shard_guard:
    def __init__(self, index=-1, stage=-1):
        raise NotImplementedError("IPU is not a target of this build")


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise NotImplementedError("IPU is not a target of this build")
