"""`paddle.fft` (reference: python/paddle/fft.py over phi fft kernels /
cuFFT; here jnp.fft → XLA FFT)."""

from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import apply

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2",
           "ifft2", "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _norm(norm):
    return None if norm == "backward" else norm


def _wrap1(jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply(lambda a: jfn(a, n=n, axis=axis, norm=_norm(norm)), x,
                     name=jfn.__name__)
    return op


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)


def _wrap2(jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply(lambda a: jfn(a, s=s, axes=axes, norm=_norm(norm)), x,
                     name=jfn.__name__)
    return op


fft2 = _wrap2(jnp.fft.fft2)
ifft2 = _wrap2(jnp.fft.ifft2)
rfft2 = _wrap2(jnp.fft.rfft2)
irfft2 = _wrap2(jnp.fft.irfft2)


def _wrapn(jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return apply(lambda a: jfn(a, s=s, axes=axes, norm=_norm(norm)), x,
                     name=jfn.__name__)
    return op


fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), x,
                 name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), x,
                 name="ifftshift")
