"""`paddle.fft` (reference: python/paddle/fft.py over phi fft kernels /
cuFFT; here jnp.fft → XLA FFT)."""

from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import apply

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2",
           "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2", "fftn", "ifftn",
           "rfftn", "irfftn", "hfftn", "ihfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _norm(norm):
    return None if norm == "backward" else norm


def _wrap1(jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply(lambda a: jfn(a, n=n, axis=axis, norm=_norm(norm)), x,
                     name=jfn.__name__)
    return op


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)


def _wrap2(jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply(lambda a: jfn(a, s=s, axes=axes, norm=_norm(norm)), x,
                     name=jfn.__name__)
    return op


fft2 = _wrap2(jnp.fft.fft2)
ifft2 = _wrap2(jnp.fft.ifft2)
rfft2 = _wrap2(jnp.fft.rfft2)
irfft2 = _wrap2(jnp.fft.irfft2)


def _wrapn(jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return apply(lambda a: jfn(a, s=s, axes=axes, norm=_norm(norm)), x,
                     name=jfn.__name__)
    return op


fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


def _hfamily_n(hermitian_1d, nd_fn, hermitian_first):
    """hfftn/ihfftn by separability (reference fft.py:827 fft_c2r/r2c
    kernels): the Hermitian axis is the last one — transform it with the
    1-D hermitian op and the remaining axes with the ordinary (i)fftn.
    Order matters for the real-typed side: ihfft (r2c) must see the REAL
    input, so it runs first; hfft (c2r) runs last."""

    def op(x, s=None, axes=None, norm="backward", name=None):
        def impl(a):
            ax = list(axes) if axes is not None else (
                list(range(a.ndim)) if s is None
                else list(range(a.ndim - len(s), a.ndim)))
            ss = list(s) if s is not None else [None] * len(ax)
            nrm = _norm(norm)
            s_rest = ss[:-1] if s is not None else None
            if hermitian_first:
                a = hermitian_1d(a, n=ss[-1], axis=ax[-1], norm=nrm)
                if len(ax) > 1:
                    a = nd_fn(a, s=s_rest, axes=ax[:-1], norm=nrm)
                return a
            if len(ax) > 1:
                a = nd_fn(a, s=s_rest, axes=ax[:-1], norm=nrm)
            return hermitian_1d(a, n=ss[-1], axis=ax[-1], norm=nrm)

        return apply(impl, x, name=name or "hfft_n")

    return op


hfftn = _hfamily_n(jnp.fft.hfft, jnp.fft.fftn, hermitian_first=False)
ihfftn = _hfamily_n(jnp.fft.ihfft, jnp.fft.ifftn, hermitian_first=True)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """2-D FFT of a Hermitian-symmetric signal (reference fft.py hfft2 =
    hfftn over two axes)."""
    return hfftn(x, s=s, axes=axes, norm=norm, name="hfft2")


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm, name="ihfft2")


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), x,
                 name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), x,
                 name="ifftshift")
