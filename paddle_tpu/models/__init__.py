"""Model zoo: LLM families mirroring the reference's headline workloads
(BASELINE.json config ladder: GPT-2, Llama, Mixtral/MoE, ViT)."""

from .gpt import GPT, GPTConfig  # noqa: F401
from .llama import Llama, LlamaConfig  # noqa: F401
from .mixtral import Mixtral, MixtralConfig  # noqa: F401
from .ppocr import (DBNet, CRNNRecognizer, PPOCRSystem,  # noqa: F401
                    db_loss)
