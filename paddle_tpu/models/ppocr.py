"""PP-OCR-style text detection + recognition models.

Capability parity with the reference's OCR story (the driver config
ladder's "PP-OCRv4" rung; reference building blocks: DB text detection
— PaddleOCR's det_db head over a light backbone — and CTC recognition —
rec_crnn/SVTR over `warpctc`, paddle/phi/kernels/impl/
warpctc_kernel_impl.h; vision ops `deform_conv2d`/`nms` live in
`vision/ops.py`).

TPU-first design:
- Everything is static-shape and jit-compilable: the DB head's
  differentiable binarization is pure elementwise math; the CTC rec
  model is conv + BiLSTM + linear over a fixed [B, 3, 32, W] strip;
  both train under `jit.TrainStep`.
- Host-side pipeline steps (box extraction from the probability map,
  crop + resize) are numpy, like the reference's postprocess ops —
  they are control flow, not compute.

Models:
- ``DBNet``: MobileNetV3-ish light backbone -> FPN-lite neck -> DB head
  (probability / threshold / approximate-binary maps), with
  ``db_loss`` (BCE on prob + L1 on thresh + dice on binary).
- ``CRNNRecognizer``: conv stack -> BiLSTM -> CTC logits, with
  ``loss`` (F.ctc_loss) and greedy ``decode``.
- ``PPOCRSystem``: det -> crop -> rec end-to-end inference.
"""

from __future__ import annotations

import math

import numpy as np

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..nn import functional as F

__all__ = ["DBNet", "CRNNRecognizer", "PPOCRSystem", "db_loss"]


class _ConvBNAct(nn.Layer):
    def __init__(self, cin, cout, k=3, stride=1, groups=1, act=True):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride,
                              padding=k // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = nn.Hardswish() if act else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act else x


class _LightBackbone(nn.Layer):
    """MobileNetV3-flavored 4-stage feature extractor (stride 4/8/16/32),
    compact enough for tests yet the same topology class PP-OCR uses."""

    def __init__(self, cin=3, widths=(16, 24, 56, 120)):
        super().__init__()
        w1, w2, w3, w4 = widths
        self.stem = _ConvBNAct(cin, w1, 3, stride=2)
        self.stage1 = nn.Sequential(_ConvBNAct(w1, w1, 3, stride=2),
                                    _ConvBNAct(w1, w1, 3))
        self.stage2 = nn.Sequential(_ConvBNAct(w1, w2, 3, stride=2),
                                    _ConvBNAct(w2, w2, 3))
        self.stage3 = nn.Sequential(_ConvBNAct(w2, w3, 3, stride=2),
                                    _ConvBNAct(w3, w3, 3))
        self.stage4 = nn.Sequential(_ConvBNAct(w3, w4, 3, stride=2),
                                    _ConvBNAct(w4, w4, 3))
        self.out_channels = widths

    def forward(self, x):
        x = self.stem(x)          # /2
        c2 = self.stage1(x)       # /4
        c3 = self.stage2(c2)      # /8
        c4 = self.stage3(c3)      # /16
        c5 = self.stage4(c4)      # /32
        return c2, c3, c4, c5


class _DBFPN(nn.Layer):
    """FPN-lite neck (PaddleOCR det_db neck): laterals + top-down adds,
    each level reduced and upsampled to /4, concatenated."""

    def __init__(self, in_channels, out_ch=96):
        super().__init__()
        self.lat = nn.LayerList([
            nn.Conv2D(c, out_ch, 1, bias_attr=False) for c in in_channels])
        self.smooth = nn.LayerList([
            nn.Conv2D(out_ch, out_ch // 4, 3, padding=1, bias_attr=False)
            for _ in in_channels])
        self.out_channels = out_ch

    def forward(self, feats):
        c2, c3, c4, c5 = feats
        p5 = self.lat[3](c5)
        p4 = self.lat[2](c4) + F.interpolate(p5, scale_factor=2,
                                             mode="nearest")
        p3 = self.lat[1](c3) + F.interpolate(p4, scale_factor=2,
                                             mode="nearest")
        p2 = self.lat[0](c2) + F.interpolate(p3, scale_factor=2,
                                             mode="nearest")
        outs = [
            self.smooth[0](p2),
            F.interpolate(self.smooth[1](p3), scale_factor=2,
                          mode="nearest"),
            F.interpolate(self.smooth[2](p4), scale_factor=4,
                          mode="nearest"),
            F.interpolate(self.smooth[3](p5), scale_factor=8,
                          mode="nearest"),
        ]
        from .. import ops
        return F.relu(ops.concat(outs, axis=1))


class _DBHead(nn.Layer):
    """Differentiable-binarization head: probability and threshold maps
    at input resolution; binary = sigmoid(k * (P - T))."""

    def __init__(self, cin, k=50.0):
        super().__init__()
        self.k = k

        def branch():
            return nn.Sequential(
                nn.Conv2D(cin, cin // 4, 3, padding=1, bias_attr=False),
                nn.BatchNorm2D(cin // 4), nn.ReLU(),
                nn.Conv2DTranspose(cin // 4, cin // 4, 2, stride=2),
                nn.BatchNorm2D(cin // 4), nn.ReLU(),
                nn.Conv2DTranspose(cin // 4, 1, 2, stride=2),
                nn.Sigmoid())

        self.prob = branch()
        self.thresh = branch()

    def forward(self, x):
        p = self.prob(x)
        t = self.thresh(x)
        b = F.sigmoid((p - t) * self.k)
        return p, t, b


class DBNet(nn.Layer):
    """DB text detector (PaddleOCR det_db architecture class)."""

    def __init__(self, in_channels=3):
        super().__init__()
        self.backbone = _LightBackbone(in_channels)
        self.neck = _DBFPN(self.backbone.out_channels)
        self.head = _DBHead(self.neck.out_channels)

    def forward(self, x):
        feats = self.backbone(x)
        fused = self.neck(feats)
        return self.head(fused)  # (prob, thresh, binary), each [B,1,H,W]

    def loss(self, x, gt_prob, gt_thresh=None, mask=None):
        p, t, b = self.forward(x)
        return db_loss(p, t, b, gt_prob, gt_thresh, mask)

    # -- host-side postprocess (reference DBPostProcess) -----------------
    @staticmethod
    def boxes_from_prob(prob_map, thresh=0.3, min_area=4):
        """Axis-aligned text boxes from the probability map via
        connected components (host numpy; returns [N, 4] x0,y0,x1,y1)."""
        binary = (np.asarray(prob_map) > thresh).astype(np.int32)
        h, w = binary.shape
        labels = np.zeros((h, w), np.int32)
        cur = 0
        boxes = []
        for i in range(h):
            for j in range(w):
                if binary[i, j] and not labels[i, j]:
                    cur += 1
                    stack = [(i, j)]
                    labels[i, j] = cur
                    x0, y0, x1, y1 = j, i, j, i
                    area = 0
                    while stack:
                        y, x = stack.pop()
                        area += 1
                        x0, x1 = min(x0, x), max(x1, x)
                        y0, y1 = min(y0, y), max(y1, y)
                        for dy, dx in ((1, 0), (-1, 0), (0, 1), (0, -1)):
                            ny, nx = y + dy, x + dx
                            if 0 <= ny < h and 0 <= nx < w and \
                                    binary[ny, nx] and not labels[ny, nx]:
                                labels[ny, nx] = cur
                                stack.append((ny, nx))
                    if area >= min_area:
                        boxes.append((x0, y0, x1 + 1, y1 + 1))
        return np.asarray(boxes, np.float32).reshape(-1, 4)


def db_loss(p, t, b, gt_prob, gt_thresh=None, mask=None,
            alpha=5.0, beta=10.0, eps=1e-6):
    """DB loss: BCE(prob) + alpha*dice(binary) + beta*L1(thresh)
    (PaddleOCR DBLoss composition)."""
    gt = gt_prob if isinstance(gt_prob, Tensor) else Tensor(gt_prob)
    bce = F.binary_cross_entropy(p, gt)
    inter = (b * gt).sum()
    dice = 1.0 - 2.0 * inter / (b.sum() + gt.sum() + eps)
    loss = bce + alpha * dice
    if gt_thresh is not None:
        gtt = gt_thresh if isinstance(gt_thresh, Tensor) \
            else Tensor(gt_thresh)
        l1 = (t - gtt).abs()
        if mask is not None:
            m = mask if isinstance(mask, Tensor) else Tensor(mask)
            l1 = (l1 * m).sum() / (m.sum() + eps)
        else:
            l1 = l1.mean()
        loss = loss + beta * l1
    return loss


class CRNNRecognizer(nn.Layer):
    """CTC text recognizer (PaddleOCR rec_crnn class): conv feature
    strip -> BiLSTM encoder -> per-column class logits; trained with
    F.ctc_loss, decoded greedily."""

    def __init__(self, num_classes, in_channels=3, hidden=96,
                 height=32):
        super().__init__()
        assert height % 16 == 0
        self.num_classes = num_classes  # incl. blank at index 0
        self.convs = nn.Sequential(
            _ConvBNAct(in_channels, 32, 3, stride=1),
            nn.MaxPool2D(2, 2),                      # H/2, W/2
            _ConvBNAct(32, 64, 3),
            nn.MaxPool2D(2, 2),                      # H/4, W/4
            _ConvBNAct(64, hidden, 3),
            nn.MaxPool2D(kernel_size=(2, 1), stride=(2, 1)),  # H/8, W/4
            _ConvBNAct(hidden, hidden, 3),
            nn.MaxPool2D(kernel_size=(2, 1), stride=(2, 1)),  # H/16
        )
        feat_h = height // 16
        self.rnn = nn.LSTM(hidden * feat_h, hidden, direction="bidirect")
        self.fc = nn.Linear(2 * hidden, num_classes)

    def logits(self, images):
        """[B, C, H, W] -> [T, B, num_classes] (T = W/4 columns)."""
        f = self.convs(images)                     # [B, ch, h', W/4]
        b, ch, hh, w = f.shape
        f = f.transpose([0, 3, 1, 2]).reshape([b, w, ch * hh])
        enc, _ = self.rnn(f)                       # [B, T, 2*hidden]
        out = self.fc(enc)                         # [B, T, C]
        return out.transpose([1, 0, 2])            # [T, B, C]

    def forward(self, images):
        return self.logits(images)

    def loss(self, images, labels, label_lengths):
        lg = self.logits(images)
        T = lg.shape[0]
        B = lg.shape[1]
        input_len = Tensor(np.full((B,), T, np.int64))
        lab = labels if isinstance(labels, Tensor) else Tensor(labels)
        ll = label_lengths if isinstance(label_lengths, Tensor) \
            else Tensor(label_lengths)
        return F.ctc_loss(lg, lab, input_len, ll, blank=0)

    def decode(self, images):
        """Greedy CTC decode -> list of class-id lists (blank=0)."""
        lg = self.logits(images)
        ids = np.asarray(jnp.argmax(lg._data, axis=-1))  # [T, B]
        outs = []
        for b in range(ids.shape[1]):
            seq = []
            prev = -1
            for t in range(ids.shape[0]):
                c = int(ids[t, b])
                if c != prev and c != 0:
                    seq.append(c)
                prev = c
            outs.append(seq)
        return outs


class PPOCRSystem:
    """det -> crop -> rec end-to-end inference (reference
    tools/infer/predict_system.py shape: detector + recognizer glue)."""

    def __init__(self, det: DBNet, rec: CRNNRecognizer, rec_height=32,
                 rec_width=100, det_thresh=0.3):
        self.det = det
        self.rec = rec
        self.rec_height = rec_height
        self.rec_width = rec_width
        self.det_thresh = det_thresh

    def __call__(self, image_np):
        """image_np [C, H, W] float32 -> list of (box, class-id list)."""
        x = Tensor(image_np[None])
        p, _t, _b = self.det(x)
        prob = np.asarray(p.numpy())[0, 0]
        boxes = DBNet.boxes_from_prob(prob, self.det_thresh)
        results = []
        for x0, y0, x1, y1 in boxes.astype(int):
            crop = image_np[:, y0:y1, x0:x1]
            if crop.shape[1] == 0 or crop.shape[2] == 0:
                continue
            crop = _resize_chw(crop, self.rec_height, self.rec_width)
            seq = self.rec.decode(Tensor(crop[None]))[0]
            results.append(((x0, y0, x1, y1), seq))
        return results


def _resize_chw(img, h, w):
    """Nearest resize [C, H, W] -> [C, h, w] (host numpy)."""
    c, ih, iw = img.shape
    yi = np.clip((np.arange(h) * ih / h).astype(int), 0, ih - 1)
    xi = np.clip((np.arange(w) * iw / w).astype(int), 0, iw - 1)
    return img[:, yi][:, :, xi].astype(np.float32)
