"""Autoregressive generation (greedy / top-k / top-p sampling).

Capability parity with the reference's decode path (masked_multihead_
attention / block_multihead_attention fused decode kernels + PaddleNLP
generate). TPU-first: the decode step is ONE jitted function over a
static-shape KV cache (dynamic_update_slice writes, length masking) —
no shape growth, no recompilation per token.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..core.tensor import Tensor

__all__ = ["generate", "sample_token"]


def sample_token(logits, temperature=1.0, top_k=0, top_p=1.0, key=None):
    """logits: [b, vocab] jnp array -> [b] int32 token ids."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k and top_k > 0:
        # clamp: top_k >= vocab keeps every token (and avoids the
        # out-of-bounds [:, -top_k] static index)
        k = min(int(top_k), logits.shape[-1])
        kth = jnp.sort(logits, axis=-1)[:, -k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def generate(model, input_ids, max_new_tokens=32, temperature=0.0,
             top_k=0, top_p=1.0, eos_token_id=None, use_cache=True):
    """Greedy (temperature=0) or sampled decoding. Returns a Tensor of
    shape [b, prompt_len + max_new_tokens]."""
    from ..core.autograd import no_grad

    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(input_ids)
    b, prompt_len = ids.shape
    max_len = prompt_len + max_new_tokens

    if not (use_cache and hasattr(model, "init_cache")):
        return _generate_no_cache(model, ids, max_new_tokens, temperature,
                                  top_k, top_p, eos_token_id)

    with no_grad():
        caches = model.init_cache(b, max_len)
        # prefill
        logits, caches = model(Tensor(ids), caches=caches,
                               position_offset=0)
        next_logits = logits._data[:, -1, :]
        cache_arrays = [(k._data, v._data) for k, v in caches]

        param_items = list(model.named_parameters())

        def step(token, cache_arrays, pos, key):
            # rebind params happens outside; model weights are already
            # concrete — call the model eagerly under trace
            caches_t = [(Tensor(k), Tensor(v)) for k, v in cache_arrays]
            logits, new_caches = model(Tensor(token[:, None]),
                                       caches=caches_t,
                                       position_offset=pos)
            nxt = sample_token(logits._data[:, -1, :], temperature, top_k,
                               top_p, key)
            return nxt, [(k._data, v._data) for k, v in new_caches]

        jit_step = jax.jit(step)

        key = random_mod.next_key()
        tok = sample_token(next_logits, temperature, top_k, top_p, key)
        out_tokens = [tok]
        done = jnp.zeros((b,), bool)
        if eos_token_id is not None:
            done = done | (tok == eos_token_id)
        for t in range(1, max_new_tokens):
            key = random_mod.next_key()
            tok, cache_arrays = jit_step(tok, cache_arrays,
                                         jnp.int32(prompt_len + t - 1),
                                         key)
            if eos_token_id is not None:
                tok = jnp.where(done, eos_token_id, tok)
                done = done | (tok == eos_token_id)
                out_tokens.append(tok)
                if bool(done.all()):
                    out_tokens.extend(
                        [jnp.full((b,), eos_token_id, jnp.int32)] *
                        (max_new_tokens - 1 - t))
                    break
            else:
                out_tokens.append(tok)
        gen = jnp.stack(out_tokens, axis=1).astype(ids.dtype)
        return Tensor(jnp.concatenate([ids, gen], axis=1))


def _generate_no_cache(model, ids, max_new_tokens, temperature, top_k,
                       top_p, eos_token_id):
    """Fallback full-context decoding for models without cache support.
    Same eos contract as the cached path: rows that hit eos keep
    emitting eos, and once every row is done the remaining positions
    fill with eos without further model calls."""
    from ..core.autograd import no_grad

    with no_grad():
        out = ids
        b = ids.shape[0]
        done = jnp.zeros((b,), bool)
        for t in range(max_new_tokens):
            logits = model(Tensor(out))
            key = random_mod.next_key()
            tok = sample_token(logits._data[:, -1, :], temperature, top_k,
                               top_p, key)
            if eos_token_id is not None:
                tok = jnp.where(done, eos_token_id, tok)
                done = done | (tok == eos_token_id)
            out = jnp.concatenate([out, tok[:, None].astype(out.dtype)],
                                  axis=1)
            if eos_token_id is not None and t < max_new_tokens - 1 \
                    and bool(done.all()):
                pad = jnp.full((b, max_new_tokens - 1 - t), eos_token_id,
                               out.dtype)
                out = jnp.concatenate([out, pad], axis=1)
                break
        return Tensor(out)
