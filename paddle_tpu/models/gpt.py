"""GPT-2 family.

Capability parity with the reference's GPT workloads (PaddleNLP GPT trained
through paddle.nn / fleet; in-repo analogues: the transformer layers of
`python/paddle/nn/layer/transformer.py` and the semi_auto_parallel llama/gpt
tests under `test/auto_parallel/hybrid_strategy/`). TPU-first choices:
- pre-LN residual blocks, learned positional embeddings (GPT-2);
- attention through F.scaled_dot_product_attention → Pallas flash kernel;
- a single weight-tied [vocab, d] embedding used for both lookup and the
  LM head matmul (one big MXU matmul, bf16-friendly);
- no data-dependent python control flow — the whole forward traces into
  one XLA program.
"""

from __future__ import annotations

import dataclasses
import math

from .. import nn
from ..nn import functional as F


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304  # padded to a 128-multiple for the MXU
    max_position_embeddings: int = 1024
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = None
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    tie_word_embeddings: bool = True
    use_fp8: bool = False  # fp8 block linears (amp.fp8 delayed scaling)
    # loss() computes CE through the blockwise fused LM-head
    # (F.fused_linear_cross_entropy) instead of materializing [b,s,V]
    # logits — the c_softmax_with_cross_entropy-class fusion
    fused_head_ce: bool = True

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @staticmethod
    def gpt2_small():
        return GPTConfig(hidden_size=768, num_layers=12, num_heads=12)

    @staticmethod
    def gpt2_medium():  # the 345M PR1 reference config
        return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16)

    @staticmethod
    def gpt2_large():
        return GPTConfig(hidden_size=1280, num_layers=36, num_heads=20)

    @staticmethod
    def tiny():  # test-sized
        return GPTConfig(vocab_size=256, max_position_embeddings=64,
                         hidden_size=64, num_layers=2, num_heads=4)


def _normal_attr(std):
    return nn.ParamAttr(initializer=nn.initializer.Normal(0.0, std))


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        d, h = config.hidden_size, config.num_heads
        self.num_heads = h
        self.head_dim = d // h
        std = config.initializer_range
        proj_std = std / math.sqrt(2 * config.num_layers)
        self.qkv_proj = nn.Linear(d, 3 * d, weight_attr=_normal_attr(std))
        self.out_proj = nn.Linear(d, d, weight_attr=_normal_attr(proj_std))
        self.dropout = config.dropout

    def forward(self, x):
        from .. import ops
        b, s, d = x.shape
        qkv = self.qkv_proj(x)
        qkv = ops.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = ops.unbind(qkv, axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=True,
            dropout_p=self.dropout if self.training else 0.0)
        out = ops.reshape(out, [b, s, d])
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        d = config.hidden_size
        std = config.initializer_range
        proj_std = std / math.sqrt(2 * config.num_layers)
        self.fc_in = nn.Linear(d, config.intermediate_size,
                               weight_attr=_normal_attr(std))
        self.fc_out = nn.Linear(config.intermediate_size, d,
                                weight_attr=_normal_attr(proj_std))

    def forward(self, x):
        return self.fc_out(F.gelu(self.fc_in(x), approximate=True))


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x):
        x = x + self.dropout(self.attn(self.ln_1(x)))
        x = x + self.dropout(self.mlp(self.ln_2(x)))
        return x


class GPT(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        std = config.initializer_range
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size,
                                weight_attr=_normal_attr(std))
        self.wpe = nn.Embedding(config.max_position_embeddings,
                                config.hidden_size,
                                weight_attr=_normal_attr(std))
        self.drop = nn.Dropout(config.dropout)
        self.h = nn.LayerList([GPTBlock(config)
                               for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)
        if not config.tie_word_embeddings:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     weight_attr=_normal_attr(std),
                                     bias_attr=False)
        else:
            self.lm_head = None
        if config.use_fp8:
            # block linears in fp8; the LM head stays bf16 (loss fidelity,
            # the standard fp8-transformer recipe)
            from ..amp.fp8 import convert_to_fp8
            convert_to_fp8(self, exclude=("lm_head",))

    def forward_hidden(self, input_ids):
        """Transformer stack output (post ln_f), before the LM head."""
        from .. import ops
        b, s = input_ids.shape
        pos = ops.arange(0, s, dtype="int64")
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        for block in self.h:
            x = block(x)
        return self.ln_f(x)

    def forward(self, input_ids):
        from .. import ops
        x = self.forward_hidden(input_ids)
        if self.lm_head is not None:
            return self.lm_head(x)
        # weight-tied head: [b,s,d] @ [d,vocab]
        return ops.matmul(x, self.wte.weight, transpose_y=True)

    def loss(self, input_ids, labels):
        """Next-token cross entropy; labels already shifted or equal to
        input_ids (we shift internally)."""
        if self.config.fused_head_ce:
            # blockwise head+CE: the [b,s,V] logits never materialize
            x = self.forward_hidden(input_ids)[:, :-1, :]
            tied = self.lm_head is None
            w = self.wte.weight if tied else self.lm_head.weight
            return F.fused_linear_cross_entropy(x, w, labels[:, 1:],
                                                transpose_weight=tied)
        logits = self(input_ids)
        shift_logits = logits[:, :-1, :]
        shift_labels = labels[:, 1:]
        return F.cross_entropy(shift_logits, shift_labels)

    def num_params(self, non_embedding=True):
        n = sum(p.size for p in self.parameters())
        if non_embedding:
            n -= self.wpe.weight.size
        return n

    def flops_per_token(self, seq_len):
        """Approximate training FLOPs/token (fwd+bwd), PaLM-style 6N + attn."""
        n = self.num_params()
        l, d = self.config.num_layers, self.config.hidden_size
        return 6 * n + 12 * l * d * seq_len

    @staticmethod
    def tp_placement_rules(mesh, tp_axis="tp"):
        """Megatron TP placements (see Llama.tp_placement_rules)."""
        from ..distributed import Replicate, Shard
        axis = mesh.dim_names.index(tp_axis)

        def P(*pairs):
            pl = [Replicate()] * mesh.ndim
            for mesh_dim, tensor_dim in pairs:
                pl[mesh_dim] = Shard(tensor_dim)
            return pl

        return [
            ("qkv_proj.weight", P((axis, 1))),
            ("qkv_proj.bias", P((axis, 0))),
            ("out_proj.weight", P((axis, 0))),
            ("fc_in.weight", P((axis, 1))),
            ("fc_in.bias", P((axis, 0))),
            ("fc_out.weight", P((axis, 0))),
            ("wte.weight", P((axis, 0))),  # vocab-parallel
        ]
