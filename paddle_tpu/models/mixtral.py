"""Mixtral (MoE Llama) family — the EP workload of BASELINE.json's config
ladder (reference analogue: incubate MoELayer + fused_moe,
python/paddle/incubate/distributed/models/moe/moe_layer.py:263)."""

from __future__ import annotations

import dataclasses

from .. import nn
from ..distributed.moe import MoELayer, TopKGate
from ..nn import functional as F
from .llama import (
    LlamaAttention, LlamaConfig, LlamaMLP, _normal_attr,
)


@dataclasses.dataclass
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01

    @staticmethod
    def mixtral_8x7b():
        return MixtralConfig(vocab_size=32000, hidden_size=4096,
                             intermediate_size=14336, num_layers=32,
                             num_heads=32, num_kv_heads=8,
                             max_position_embeddings=32768,
                             rope_theta=1e6, num_experts=8, top_k=2)

    @staticmethod
    def tiny():
        return MixtralConfig(vocab_size=256, hidden_size=64,
                             intermediate_size=128, num_layers=2,
                             num_heads=4, num_kv_heads=2,
                             max_position_embeddings=64, num_experts=4,
                             top_k=2)


class MixtralBlock(nn.Layer):
    def __init__(self, config: MixtralConfig, mesh=None, ep_axis=None):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(
            config.hidden_size, epsilon=config.rms_norm_eps)
        experts = [LlamaMLP(config) for _ in range(config.num_experts)]
        gate = TopKGate(config.hidden_size, config.num_experts,
                        top_k=config.top_k,
                        capacity_factor=config.capacity_factor)
        self.moe = MoELayer(gate=gate, experts=experts, mesh=mesh,
                            ep_axis=ep_axis)

    def forward(self, x):
        x = x + self.self_attn(self.input_layernorm(x))
        x = x + self.moe(self.post_attention_layernorm(x))
        return x


class Mixtral(nn.Layer):
    def __init__(self, config: MixtralConfig, mesh=None, ep_axis=None):
        super().__init__()
        self.config = config
        std = config.initializer_range
        self.embed_tokens = nn.Embedding(config.vocab_size,
                                         config.hidden_size,
                                         weight_attr=_normal_attr(std))
        self.layers = nn.LayerList(
            [MixtralBlock(config, mesh=mesh, ep_axis=ep_axis)
             for _ in range(config.num_layers)])
        self.norm = nn.RMSNorm(config.hidden_size,
                               epsilon=config.rms_norm_eps)
        self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                 weight_attr=_normal_attr(std),
                                 bias_attr=False)

    def forward(self, input_ids):
        x = self.embed_tokens(input_ids)
        for block in self.layers:
            x = block(x)
        return self.lm_head(self.norm(x))

    def aux_loss(self):
        from .. import ops
        total = None
        for block in self.layers:
            a = block.moe.aux_loss
            if a is not None:
                total = a if total is None else total + a
        return total

    def loss(self, input_ids, labels):
        logits = self(input_ids)
        ce = F.cross_entropy(logits[:, :-1, :], labels[:, 1:])
        aux = self.aux_loss()
        if aux is not None:
            ce = ce + self.config.aux_loss_weight * aux
        return ce

    def num_params(self):
        return sum(p.size for p in self.parameters())
