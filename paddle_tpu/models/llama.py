"""Llama family (Llama-2/3 style decoder).

Capability parity target: the reference's semi-auto llama workload
(`test/auto_parallel/hybrid_strategy/semi_auto_llama.py`) and its fused
kernels (`fused_rope`, `fused_rms_norm`, flash attention — SURVEY.md §2.1).
TPU-first: RoPE and RMSNorm are plain jnp (XLA fuses them into neighbors),
attention is SDPA→Pallas flash with GQA, SwiGLU is two MXU matmuls + fused
elementwise. No KV-cache branching in the training path.
"""

from __future__ import annotations

import dataclasses
import threading

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn import functional as F

# guards lazy creation of each model's paged-call lock (Llama._paged_lock)
_PAGED_LOCK_INIT = threading.Lock()


def _aot_wrap(jitted, tag):
    """Route a serving-path jit entry point through the persistent AOT
    compile cache (serving/aot_cache.py): a fresh process with a warm
    cache loads the serialized executable instead of compiling. The
    wrapper forwards straight to ``jitted`` until a cache dir is
    configured (FLAGS_serving_aot_cache / FLAGS_aot_cache_dir), so the
    production default is byte-for-byte plain jax.jit."""
    try:
        from ..serving.aot_cache import wrap
        return wrap(jitted, tag)
    except Exception:  # noqa: BLE001 — a broken cache layer must not block serving
        return jitted


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = None  # GQA; None = MHA
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    use_fp8: bool = False  # fp8 block linears (amp.fp8 delayed scaling)
    # loss() uses the blockwise fused LM-head CE (see models/gpt.py)
    fused_head_ce: bool = True

    def __post_init__(self):
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads

    @staticmethod
    def llama2_7b():
        return LlamaConfig()

    @staticmethod
    def llama3_8b():
        return LlamaConfig(vocab_size=128256, hidden_size=4096,
                           intermediate_size=14336, num_layers=32,
                           num_heads=32, num_kv_heads=8,
                           max_position_embeddings=8192,
                           rope_theta=500000.0)

    @staticmethod
    def llama3_70b():
        return LlamaConfig(vocab_size=128256, hidden_size=8192,
                           intermediate_size=28672, num_layers=80,
                           num_heads=64, num_kv_heads=8,
                           max_position_embeddings=8192,
                           rope_theta=500000.0)

    @staticmethod
    def tiny():
        return LlamaConfig(vocab_size=256, hidden_size=64,
                           intermediate_size=128, num_layers=2, num_heads=4,
                           num_kv_heads=2, max_position_embeddings=64)

    @staticmethod
    def tiny_tp():
        """Mesh-friendly tiny config (docs/SERVING.md "Mesh-sharded
        serving"): 8 q and kv heads so the serving mesh's model axis
        can split 1..8 ways — ``tiny()``'s 4/2 heads cap it at 2.
        tools/mesh_gate.py, bench.py's ``mesh_serve`` rung, and
        tests/framework/test_mesh_serving.py all serve THIS config."""
        return LlamaConfig(vocab_size=256, hidden_size=64,
                           intermediate_size=128, num_layers=2,
                           num_heads=8, num_kv_heads=8,
                           max_position_embeddings=64)


def apply_rope(q, k, theta=10000.0, position_offset=0):
    """Rotary embedding on [b, s, h, d] Tensors (capability of the
    reference's fused_rotary_position_embedding, fused_ops.yaml:408)."""

    def _rope(qa, ka):
        d = qa.shape[-1]
        s = qa.shape[1]
        inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, jnp.float32) / d))
        off = jnp.asarray(position_offset, jnp.float32)
        if off.ndim == 1:  # per-batch offsets (paged decode slots)
            pos = off[:, None] + jnp.arange(s, dtype=jnp.float32)[None, :]
            freqs = pos[..., None] * inv_freq  # [b, s, d/2]
            cos = jnp.cos(freqs)[:, :, None, :]
            sin = jnp.sin(freqs)[:, :, None, :]
        else:
            pos = off + jnp.arange(s, dtype=jnp.float32)
            freqs = jnp.outer(pos, inv_freq)  # [s, d/2]
            cos = jnp.cos(freqs)[None, :, None, :]
            sin = jnp.sin(freqs)[None, :, None, :]

        def rot(x):
            x1 = x[..., 0::2].astype(jnp.float32)
            x2 = x[..., 1::2].astype(jnp.float32)
            o1 = x1 * cos - x2 * sin
            o2 = x2 * cos + x1 * sin
            out = jnp.stack([o1, o2], axis=-1).reshape(x.shape)
            return out.astype(x.dtype)

        return rot(qa), rot(ka)

    return apply(_rope, q, k, name="rope")


def _normal_attr(std):
    return nn.ParamAttr(initializer=nn.initializer.Normal(0.0, std))


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        d = config.hidden_size
        self.num_heads = config.num_heads
        self.num_kv_heads = config.num_kv_heads
        self.head_dim = d // config.num_heads
        self.rope_theta = config.rope_theta
        std = config.initializer_range
        kv_out = self.num_kv_heads * self.head_dim
        self.q_proj = nn.Linear(d, d, weight_attr=_normal_attr(std),
                                bias_attr=False)
        self.k_proj = nn.Linear(d, kv_out, weight_attr=_normal_attr(std),
                                bias_attr=False)
        self.v_proj = nn.Linear(d, kv_out, weight_attr=_normal_attr(std),
                                bias_attr=False)
        self.o_proj = nn.Linear(d, d, weight_attr=_normal_attr(std),
                                bias_attr=False)

    def forward(self, x, cache=None, position_offset=0, kv_sink=None):
        from .. import ops
        b, s, d = x.shape
        q = ops.reshape(self.q_proj(x), [b, s, self.num_heads, self.head_dim])
        k = ops.reshape(self.k_proj(x),
                        [b, s, self.num_kv_heads, self.head_dim])
        v = ops.reshape(self.v_proj(x),
                        [b, s, self.num_kv_heads, self.head_dim])
        q, k = apply_rope(q, k, theta=self.rope_theta,
                          position_offset=position_offset)
        if kv_sink is not None:  # paged prefill captures post-rope KV
            kv_sink.append((k, v))
        if cache is None:
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
            out = ops.reshape(out, [b, s, d])
            return self.o_proj(out)
        # decode/prefill with KV cache: cache = (k_cache, v_cache)
        # [b, max_s, kv_heads, head_dim] Tensors; write at position_offset,
        # attend against positions <= query position (static shapes for jit)
        k_cache, v_cache = cache

        def attend(qa, ka, va, kc, vc, off):
            z = jnp.int32(0)
            off32 = jnp.asarray(off, jnp.int32)
            kc = jax.lax.dynamic_update_slice(kc, ka.astype(kc.dtype),
                                              (z, off32, z, z))
            vc = jax.lax.dynamic_update_slice(vc, va.astype(vc.dtype),
                                              (z, off32, z, z))
            max_s = kc.shape[1]
            rep = qa.shape[2] // kc.shape[2]
            kf = jnp.repeat(kc, rep, axis=2) if rep > 1 else kc
            vf = jnp.repeat(vc, rep, axis=2) if rep > 1 else vc
            scale = 1.0 / (qa.shape[-1] ** 0.5)
            logits = jnp.einsum("bsnd,btnd->bnst", qa, kf,
                                preferred_element_type=jnp.float32) * scale
            pos_q = off + jnp.arange(qa.shape[1], dtype=jnp.int32)
            pos_k = jnp.arange(max_s, dtype=jnp.int32)
            mask = pos_k[None, :] <= pos_q[:, None]
            logits = jnp.where(mask[None, None], logits, -1e30)
            probs = jax.nn.softmax(logits, axis=-1).astype(qa.dtype)
            out = jnp.einsum("bnst,btnd->bsnd", probs, vf)
            return out, kc, vc

        out, new_k, new_v = apply(attend, q, k, v, k_cache, v_cache,
                                  position_offset, name="cached_attention")
        out = ops.reshape(out, [b, s, d])
        return self.o_proj(out), (new_k, new_v)


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        d, i = config.hidden_size, config.intermediate_size
        std = config.initializer_range
        self.gate_proj = nn.Linear(d, i, weight_attr=_normal_attr(std),
                                   bias_attr=False)
        self.up_proj = nn.Linear(d, i, weight_attr=_normal_attr(std),
                                 bias_attr=False)
        self.down_proj = nn.Linear(i, d, weight_attr=_normal_attr(std),
                                   bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaBlock(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(
            config.hidden_size, epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, cache=None, position_offset=0, kv_sink=None):
        if cache is None:
            x = x + self.self_attn(self.input_layernorm(x),
                                   kv_sink=kv_sink)
            x = x + self.mlp(self.post_attention_layernorm(x))
            return x
        attn_out, new_cache = self.self_attn(
            self.input_layernorm(x), cache=cache,
            position_offset=position_offset)
        x = x + attn_out
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x, new_cache


class Llama(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        std = config.initializer_range
        self.embed_tokens = nn.Embedding(config.vocab_size,
                                         config.hidden_size,
                                         weight_attr=_normal_attr(std))
        self.layers = nn.LayerList([LlamaBlock(config)
                                    for _ in range(config.num_layers)])
        self.norm = nn.RMSNorm(config.hidden_size,
                               epsilon=config.rms_norm_eps)
        if not config.tie_word_embeddings:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     weight_attr=_normal_attr(std),
                                     bias_attr=False)
        else:
            self.lm_head = None
        if config.use_fp8:
            from ..amp.fp8 import convert_to_fp8
            convert_to_fp8(self, exclude=("lm_head",))

    def forward(self, input_ids, caches=None, position_offset=0,
                kv_sink=None):
        from .. import ops
        new_caches = None
        if caches is None:
            x = self.forward_hidden(input_ids, kv_sink=kv_sink)
        else:
            x = self.embed_tokens(input_ids)
            new_caches = []
            for i, block in enumerate(self.layers):
                x, c = block(x, cache=caches[i],
                             position_offset=position_offset)
                new_caches.append(c)
            x = self.norm(x)
        if self.lm_head is not None:
            logits = self.lm_head(x)
        else:
            logits = ops.matmul(x, self.embed_tokens.weight,
                                transpose_y=True)
        if caches is None:
            return logits
        return logits, new_caches

    def init_cache(self, batch_size, max_seq_len, dtype=None):
        """Allocate empty KV caches: per layer (k, v) of
        [b, max_s, kv_heads, head_dim]."""
        from .. import ops
        dt = dtype or (self.embed_tokens.weight.dtype)
        kvh = self.config.num_kv_heads
        hd = self.config.hidden_size // self.config.num_heads
        return [(ops.zeros([batch_size, max_seq_len, kvh, hd], dt),
                 ops.zeros([batch_size, max_seq_len, kvh, hd], dt))
                for _ in range(self.config.num_layers)]

    def generate(self, input_ids, max_new_tokens=32, **kwargs):
        from .generation import generate
        return generate(self, input_ids, max_new_tokens=max_new_tokens,
                        **kwargs)

    # -- paged (block) KV-cache decode ------------------------------------
    # Reference: block_multi_head_attention_kernel.cu (paged cache) +
    # masked_multihead_attention_kernel.cu (decode). See inference/paged.py.

    def _param_rebind(self):
        if not hasattr(self, "_pb_names"):
            self._pb_names = [n for n, _ in self.named_parameters()]
        if hasattr(self, "_pb_rebind"):
            return self._pb_rebind

        def rebind(param_arrays):
            for n, arr in zip(self._pb_names, param_arrays):
                obj = self
                *path, leaf = n.split(".")
                for seg in path:
                    obj = obj[int(seg)] if seg.isdigit() else \
                        getattr(obj, seg)
                getattr(obj, leaf)._data = arr
        self._pb_rebind = rebind
        return rebind

    def _param_arrays(self):
        return tuple(p._data for _, p in self.named_parameters())

    # every jitted serving entry point this model caches; cleared when
    # the serving mesh changes so programs re-lower against the new
    # shardings (and re-fingerprint in the AOT cache under the new tag)
    _PAGED_JIT_ATTRS = ("_paged_prefill_jit", "_paged_extend_jit",
                       "_paged_extend_q8_jit", "_paged_decode_jit",
                       "_paged_decode_q8_jit", "_paged_spec_jit",
                       "_paged_spec_q8_jit")

    def serving_mesh(self):
        """The ServingMesh this model's serving params are laid out
        on, or None (single-device serving)."""
        return self.__dict__.get("_serving_mesh")

    def apply_serving_mesh(self, mesh):
        """Lay the model out for mesh-sharded serving
        (serving/mesh.py; docs/SERVING.md "Mesh-sharded serving"):
        every parameter is ``device_put`` with its ``NamedSharding``
        along the mesh's model axis (column-parallel q/k/v/gate/up,
        row-parallel o/down, everything else replicated) and the
        cached paged jit entry points drop so they re-lower sharded —
        their AOT tags fold the mesh shape in (``_aot_tag``), so a
        1x8 executable can never be served from a 1x1 cache entry.
        Idempotent for the same mesh spec; ``mesh=None`` is a no-op
        (a previously-meshed model keeps its layout — construct a
        fresh model for single-device serving)."""
        if mesh is None:
            return
        import jax

        mesh.validate_model(self.config)
        cur = self.__dict__.get("_serving_mesh")
        if cur is not None and cur.spec == mesh.spec:
            self.__dict__["_serving_mesh"] = mesh
            return
        with self._paged_lock():
            for n, p in self.named_parameters():
                p._data = jax.device_put(p._data, mesh.param_sharding(n))
            self.__dict__["_serving_mesh"] = mesh
            for attr in self._PAGED_JIT_ATTRS:
                self.__dict__.pop(attr, None)

    def _aot_tag(self, base):
        """AOT-cache tag for a serving program: the mesh spec folds in
        so fingerprints differ across mesh shapes even where the
        lowered text happens to agree (tests/framework/
        test_mesh_serving.py pins the distinction)."""
        mesh = self.__dict__.get("_serving_mesh")
        return base if mesh is None else f"{base}.mesh{mesh.spec}"

    def _paged_lock(self):
        """Per-model lock serializing the paged jit entry points. Their
        trace path REBINDS the module's parameters to tracers and
        restores them after the call — with several serving engines
        sharing one model (in-process fleet replicas), an unsynchronized
        cold-start races another thread's restore and leaks tracers into
        the shared params. One uncontended acquire per warm call is
        noise next to the dispatch itself. Created lazily in __dict__
        (not through Layer attr tracking; models stay picklable until
        first serve)."""
        lock = self.__dict__.get("_paged_call_lock")
        if lock is None:
            with _PAGED_LOCK_INIT:
                lock = self.__dict__.get("_paged_call_lock")
                if lock is None:
                    lock = threading.Lock()
                    self.__dict__["_paged_call_lock"] = lock
        return lock

    def paged_prefill(self, cache, slot, prompt_ids, temperature=0.0,
                      pad_to=None):
        """Run the prompt through the dense forward (causal), write its
        post-rope KV into the slot's pool blocks, set seq_len, and return
        the first sampled token.

        ``pad_to`` (serving/bucketing.py): pad the prompt to a bucketed
        length instead of the next block multiple, so warm serving traces
        a bounded set of prefill shapes. Padding beyond the slot's
        allocated blocks is safe: the extra table entries are 0, the
        reserved null block, and everything past ``true_len`` is masked.
        """
        from ..core.random import next_key
        from ..inference.paged import paged_prefill_write

        prompt = np.asarray(prompt_ids).reshape(-1)
        s = prompt.shape[0]
        bs = cache.block_size
        spad = -(-s // bs) * bs
        if pad_to is not None:
            cap = cache.max_blocks_per_seq * bs
            want = min(max(int(pad_to), spad), cap)
            spad = -(-want // bs) * bs
        ids = np.zeros((1, spad), np.int64)
        ids[:, :s] = prompt

        if not hasattr(self, "_paged_prefill_jit"):
            rebind = self._param_rebind()

            def fn(param_arrays, ids_arr, true_len, key, temp):
                from .generation import sample_token
                rebind(param_arrays)
                sink = []
                from ..core.autograd import no_grad
                with no_grad():
                    logits = self.forward(Tensor(ids_arr), kv_sink=sink)
                last = jnp.take_along_axis(
                    logits._data, (true_len - 1)[None, None, None],
                    axis=1)[:, 0]
                tok = jax.lax.cond(
                    temp > 0,
                    lambda: sample_token(last / jnp.maximum(temp, 1e-6),
                                         temperature=1.0, key=key),
                    lambda: jnp.argmax(last, axis=-1).astype(jnp.int32))
                ks = [k._data[0] for k, _ in sink]
                vs = [v._data[0] for _, v in sink]
                return tok[0], ks, vs
            self._paged_prefill_jit = _aot_wrap(
                jax.jit(fn), self._aot_tag("llama.paged_prefill"))

        with self._paged_lock():
            arrs = self._param_arrays()
            tok, ks, vs = self._paged_prefill_jit(
                arrs, jnp.asarray(ids), jnp.int32(s),
                next_key(), jnp.float32(temperature))
            # tracing left tracers bound into the module params; restore
            self._param_rebind()(arrs)
        row = cache.block_tables[slot]
        for i in range(cache.num_layers):
            if cache.quantized:
                from ..inference.paged import paged_prefill_write_q
                (cache.k_pools[i], cache.v_pools[i],
                 cache.k_scales[i], cache.v_scales[i]) = \
                    paged_prefill_write_q(
                        cache.k_pools[i], cache.v_pools[i],
                        cache.k_scales[i], cache.v_scales[i],
                        row, ks[i], vs[i])
            else:
                cache.k_pools[i], cache.v_pools[i] = paged_prefill_write(
                    cache.k_pools[i], cache.v_pools[i], row, ks[i],
                    vs[i])
        cache.seq_lens[slot] = s
        return int(tok)

    def paged_prefill_extend(self, cache, slot, ids, tail_start,
                             write_start, temperature=0.0, pad_to=None):
        """Prefix-cache prefill (inference/paged.py): the slot's block
        table already maps cached KV for positions ``[0, tail_start)``
        (mapped read-only at admission); compute ONLY the tail
        ``ids[tail_start:]`` — embed, rope at the absolute offset, write
        its KV into the pool (positions ``>= write_start`` only; a
        fully-covered prompt recomputes just its last token's query and
        writes nothing), and attend each tail token against the whole
        paged context. Sets seq_len and returns the first sampled token,
        exactly like ``paged_prefill`` — covered positions cost zero
        prefill FLOPs.

        ``pad_to`` buckets the TAIL length (serving/bucketing.py) so
        warm cache-hit traffic traces a bounded set of extend programs;
        padded rows write nothing (masked to the null block) and their
        outputs are never read.
        """
        from ..core.random import next_key

        ids = np.asarray(ids).reshape(-1)
        total = ids.shape[0]
        bs = cache.block_size
        s_tail = total - tail_start
        spad = -(-s_tail // bs) * bs
        if pad_to is not None:
            cap = cache.max_blocks_per_seq * bs
            want = min(max(int(pad_to), spad), cap)
            spad = -(-want // bs) * bs
        tail = np.zeros((1, spad), np.int64)
        tail[0, :s_tail] = ids[tail_start:]

        if cache.quantized:
            # int8 pools thread their scale arrays through the program
            # and dequantize at the gathers; its own jit + AOT tag so a
            # model can serve quantized and full-precision caches
            # side by side
            if getattr(self, "_paged_extend_q8_jit", None) is None:
                self._paged_extend_q8_jit = self._build_extend_q8()
            with self._paged_lock():
                arrs = self._param_arrays()
                tok, ks, vs, kss, vss = self._paged_extend_q8_jit(
                    arrs, jnp.asarray(tail), jnp.int32(tail_start),
                    jnp.int32(write_start), jnp.int32(total),
                    jnp.asarray(cache.block_tables[slot]),
                    cache.k_pools, cache.v_pools,
                    cache.k_scales, cache.v_scales, next_key(),
                    jnp.float32(temperature))
                self._param_rebind()(arrs)
            cache.k_pools = list(ks)
            cache.v_pools = list(vs)
            cache.k_scales = list(kss)
            cache.v_scales = list(vss)
            cache.seq_lens[slot] = total
            return int(tok)

        if not hasattr(self, "_paged_extend_jit"):
            rebind = self._param_rebind()
            cfg = self.config
            hq = cfg.num_heads
            hk = cfg.num_kv_heads
            hd = cfg.hidden_size // hq

            def fn(param_arrays, tail_ids, t_start, w_start, t_total,
                   row, k_pools, v_pools, key, temp):
                from ..inference.paged import (
                    paged_prefill_write_masked,
                    paged_prefix_attention_dense)
                from .generation import sample_token
                from ..core.autograd import no_grad
                rebind(param_arrays)
                s = tail_ids.shape[1]
                with no_grad():
                    x = self.embed_tokens(Tensor(tail_ids))
                    new_k, new_v = [], []
                    for i, blk in enumerate(self.layers):
                        attn = blk.self_attn
                        h = blk.input_layernorm(x)
                        q = attn.q_proj(h).reshape([1, s, hq, hd])
                        k = attn.k_proj(h).reshape([1, s, hk, hd])
                        v = attn.v_proj(h).reshape([1, s, hk, hd])
                        q, k = apply_rope(q, k, theta=attn.rope_theta,
                                          position_offset=t_start)
                        kp, vp = paged_prefill_write_masked(
                            k_pools[i], v_pools[i], row, k._data[0],
                            v._data[0], t_start, w_start, t_total)
                        out = paged_prefix_attention_dense(
                            q._data[0], kp, vp, row, t_start, t_total)
                        x = x + attn.o_proj(
                            Tensor(out.reshape(1, s, hq * hd)))
                        x = x + blk.mlp(blk.post_attention_layernorm(x))
                        new_k.append(kp)
                        new_v.append(vp)
                    x = self.norm(x)
                    if self.lm_head is not None:
                        logits = self.lm_head(x)
                    else:
                        from .. import ops
                        logits = ops.matmul(x, self.embed_tokens.weight,
                                            transpose_y=True)
                last = jnp.take_along_axis(
                    logits._data, (t_total - 1 - t_start)[None, None,
                                                          None],
                    axis=1)[:, 0]
                tok = jax.lax.cond(
                    temp > 0,
                    lambda: sample_token(last / jnp.maximum(temp, 1e-6),
                                         temperature=1.0, key=key),
                    lambda: jnp.argmax(last, axis=-1).astype(jnp.int32))
                return tok[0], new_k, new_v
            self._paged_extend_jit = _aot_wrap(
                jax.jit(fn), self._aot_tag("llama.paged_extend"))

        with self._paged_lock():
            arrs = self._param_arrays()
            tok, ks, vs = self._paged_extend_jit(
                arrs, jnp.asarray(tail), jnp.int32(tail_start),
                jnp.int32(write_start), jnp.int32(total),
                jnp.asarray(cache.block_tables[slot]),
                cache.k_pools, cache.v_pools, next_key(),
                jnp.float32(temperature))
            self._param_rebind()(arrs)
        cache.k_pools = list(ks)
        cache.v_pools = list(vs)
        cache.seq_lens[slot] = total
        return int(tok)

    def _build_extend_q8(self):
        """Quantized twin of the `_paged_extend_jit` program
        (FLAGS_kv_cache_dtype=int8): identical structure, but tail KV
        quantizes per (position, kv-head) on write
        (`paged_prefill_write_masked_q`) and the prefix attention
        dequantizes in its gather."""
        rebind = self._param_rebind()
        cfg = self.config
        hq = cfg.num_heads
        hk = cfg.num_kv_heads
        hd = cfg.hidden_size // hq

        def fn(param_arrays, tail_ids, t_start, w_start, t_total, row,
               k_pools, v_pools, k_scales, v_scales, key, temp):
            from ..core.autograd import no_grad
            from ..inference.paged import (paged_prefill_write_masked_q,
                                           paged_prefix_attention_dense)
            from .generation import sample_token
            rebind(param_arrays)
            s = tail_ids.shape[1]
            with no_grad():
                x = self.embed_tokens(Tensor(tail_ids))
                new_k, new_v, new_ks, new_vs = [], [], [], []
                for i, blk in enumerate(self.layers):
                    attn = blk.self_attn
                    h = blk.input_layernorm(x)
                    q = attn.q_proj(h).reshape([1, s, hq, hd])
                    k = attn.k_proj(h).reshape([1, s, hk, hd])
                    v = attn.v_proj(h).reshape([1, s, hk, hd])
                    q, k = apply_rope(q, k, theta=attn.rope_theta,
                                      position_offset=t_start)
                    kp, vp, ksc, vsc = paged_prefill_write_masked_q(
                        k_pools[i], v_pools[i], k_scales[i],
                        v_scales[i], row, k._data[0], v._data[0],
                        t_start, w_start, t_total)
                    out = paged_prefix_attention_dense(
                        q._data[0], kp, vp, row, t_start, t_total,
                        k_scale=ksc, v_scale=vsc)
                    x = x + attn.o_proj(
                        Tensor(out.reshape(1, s, hq * hd)))
                    x = x + blk.mlp(blk.post_attention_layernorm(x))
                    new_k.append(kp)
                    new_v.append(vp)
                    new_ks.append(ksc)
                    new_vs.append(vsc)
                x = self.norm(x)
                if self.lm_head is not None:
                    logits = self.lm_head(x)
                else:
                    from .. import ops
                    logits = ops.matmul(x, self.embed_tokens.weight,
                                        transpose_y=True)
            last = jnp.take_along_axis(
                logits._data, (t_total - 1 - t_start)[None, None, None],
                axis=1)[:, 0]
            tok = jax.lax.cond(
                temp > 0,
                lambda: sample_token(last / jnp.maximum(temp, 1e-6),
                                     temperature=1.0, key=key),
                lambda: jnp.argmax(last, axis=-1).astype(jnp.int32))
            return tok[0], new_k, new_v, new_ks, new_vs
        return _aot_wrap(jax.jit(fn),
                         self._aot_tag("llama.paged_extend.q8"))

    def paged_decode_step(self, cache, last_tokens, active,
                          temperature=0.0, kernel_mode=None):
        """One decode step for every live slot: write the incoming token's
        KV at position seq_len, attend against the paged cache (masked to
        seq_len+1), sample the next token. Single static-shape jitted
        program; updates `cache` pools/lens in place.

        ``kernel_mode`` is the engine's construction-resolved
        ``FLAGS_paged_kernel`` (auto|pallas|dense) — it picks the
        attention route inside the traced program, so the decode jits
        cache PER MODE (engines with different routing can share one
        model without serving each other's programs)."""
        from ..core.random import next_key
        from ..inference.paged import resolve_paged_kernel

        mode = resolve_paged_kernel(kernel_mode)

        if cache.quantized:
            jits = self.__dict__.setdefault("_paged_decode_q8_jit", {})
            if jits.get(mode) is None:
                jits[mode] = self._build_decode_q8(mode)
            step = jits[mode]
            with self._paged_lock():
                arrs = self._param_arrays()
                toks, nk, nv, nks, nvs = step(
                    arrs, jnp.asarray(last_tokens, jnp.int32),
                    cache.k_pools, cache.v_pools, cache.k_scales,
                    cache.v_scales, cache.block_tables,
                    jnp.asarray(cache.seq_lens), jnp.asarray(active),
                    next_key(), jnp.float32(temperature))
                self._param_rebind()(arrs)
            cache.k_pools = list(nk)
            cache.v_pools = list(nv)
            cache.k_scales = list(nks)
            cache.v_scales = list(nvs)
            act = np.asarray(active)
            cache.seq_lens = np.where(act, cache.seq_lens + 1,
                                      cache.seq_lens).astype(np.int32)
            return toks

        jits = self.__dict__.setdefault("_paged_decode_jit", {})
        if jits.get(mode) is None:
            rebind = self._param_rebind()
            cfg = self.config
            hq = cfg.num_heads
            hk = cfg.num_kv_heads
            hd = cfg.hidden_size // hq
            # mesh-sharded serving: captured at build time — the jit is
            # rebuilt (apply_serving_mesh clears it) when the mesh
            # changes. With stable shard_map available the attention
            # runs explicitly sharded per kv-head; otherwise the same
            # layout rides the NamedSharding inputs + GSPMD.
            mesh = self.__dict__.get("_serving_mesh")
            use_tp = mesh is not None and mesh.shard_map_armed

            def fn(param_arrays, toks, k_pools, v_pools, tables, lens,
                   active, key, temp):
                from ..inference.paged import (paged_decode_attention,
                                               paged_decode_attention_tp,
                                               paged_decode_write)
                from .generation import sample_token
                from ..core.autograd import no_grad
                rebind(param_arrays)
                b = toks.shape[0]
                with no_grad():
                    x = self.embed_tokens(Tensor(toks[:, None]))
                    new_k, new_v = [], []
                    for i, blk in enumerate(self.layers):
                        attn = blk.self_attn
                        h = blk.input_layernorm(x)
                        q = attn.q_proj(h).reshape([b, 1, hq, hd])
                        k = attn.k_proj(h).reshape([b, 1, hk, hd])
                        v = attn.v_proj(h).reshape([b, 1, hk, hd])
                        q, k = apply_rope(q, k, theta=attn.rope_theta,
                                          position_offset=lens)
                        kp, vp = paged_decode_write(
                            k_pools[i], v_pools[i], tables, lens,
                            k._data[:, 0], v._data[:, 0], active)
                        if use_tp:
                            out = paged_decode_attention_tp(
                                q._data[:, 0], kp, vp, tables,
                                jnp.where(active, lens + 1, lens), mesh,
                                kernel_mode=mode)
                        else:
                            out = paged_decode_attention(
                                q._data[:, 0], kp, vp, tables,
                                jnp.where(active, lens + 1, lens),
                                kernel_mode=mode)
                        x = x + attn.o_proj(
                            Tensor(out.reshape(b, 1, hq * hd)))
                        x = x + blk.mlp(blk.post_attention_layernorm(x))
                        new_k.append(kp)
                        new_v.append(vp)
                    x = self.norm(x)
                    if self.lm_head is not None:
                        logits = self.lm_head(x)
                    else:
                        from .. import ops
                        logits = ops.matmul(x, self.embed_tokens.weight,
                                            transpose_y=True)
                last = logits._data[:, 0]
                nxt = jax.lax.cond(
                    temp > 0,
                    lambda: sample_token(last / jnp.maximum(temp, 1e-6),
                                         temperature=1.0, key=key),
                    lambda: jnp.argmax(last, axis=-1).astype(jnp.int32))
                return nxt, new_k, new_v
            tag = "llama.paged_decode" + (
                "" if mode == "auto" else f".k-{mode}")
            jits[mode] = _aot_wrap(jax.jit(fn), self._aot_tag(tag))
        step = jits[mode]

        with self._paged_lock():
            arrs = self._param_arrays()
            toks, new_k, new_v = step(
                arrs, jnp.asarray(last_tokens, jnp.int32),
                cache.k_pools, cache.v_pools, cache.block_tables,
                jnp.asarray(cache.seq_lens), jnp.asarray(active),
                next_key(),
                jnp.float32(temperature))
            self._param_rebind()(arrs)
        cache.k_pools = list(new_k)
        cache.v_pools = list(new_v)
        act = np.asarray(active)
        cache.seq_lens = np.where(act, cache.seq_lens + 1,
                                  cache.seq_lens).astype(np.int32)
        return toks

    def _build_decode_q8(self, kernel_mode="auto"):
        """Quantized twin of the `_paged_decode_jit` program: the
        incoming token's KV quantizes on write (`paged_decode_write_q`)
        and the attention dequantizes the int8 pool per routing mode —
        fused inside the Pallas kernel's VMEM gather on the pallas
        route, or in the dense reference's XLA gather when
        ``kernel_mode`` forces dense (or auto resolves there)."""
        rebind = self._param_rebind()
        cfg = self.config
        hq = cfg.num_heads
        hk = cfg.num_kv_heads
        hd = cfg.hidden_size // hq
        mesh = self.__dict__.get("_serving_mesh")
        use_tp = mesh is not None and mesh.shard_map_armed

        def fn(param_arrays, toks, k_pools, v_pools, k_scales, v_scales,
               tables, lens, active, key, temp):
            from ..core.autograd import no_grad
            from ..inference.paged import (paged_decode_attention,
                                           paged_decode_attention_tp,
                                           paged_decode_write_q)
            from .generation import sample_token
            rebind(param_arrays)
            b = toks.shape[0]
            with no_grad():
                x = self.embed_tokens(Tensor(toks[:, None]))
                new_k, new_v, new_ks, new_vs = [], [], [], []
                for i, blk in enumerate(self.layers):
                    attn = blk.self_attn
                    h = blk.input_layernorm(x)
                    q = attn.q_proj(h).reshape([b, 1, hq, hd])
                    k = attn.k_proj(h).reshape([b, 1, hk, hd])
                    v = attn.v_proj(h).reshape([b, 1, hk, hd])
                    q, k = apply_rope(q, k, theta=attn.rope_theta,
                                      position_offset=lens)
                    kp, vp, ksc, vsc = paged_decode_write_q(
                        k_pools[i], v_pools[i], k_scales[i],
                        v_scales[i], tables, lens, k._data[:, 0],
                        v._data[:, 0], active)
                    if use_tp:
                        out = paged_decode_attention_tp(
                            q._data[:, 0], kp, vp, tables,
                            jnp.where(active, lens + 1, lens), mesh,
                            k_scale=ksc, v_scale=vsc,
                            kernel_mode=kernel_mode)
                    else:
                        out = paged_decode_attention(
                            q._data[:, 0], kp, vp, tables,
                            jnp.where(active, lens + 1, lens),
                            k_scale=ksc, v_scale=vsc,
                            kernel_mode=kernel_mode)
                    x = x + attn.o_proj(
                        Tensor(out.reshape(b, 1, hq * hd)))
                    x = x + blk.mlp(blk.post_attention_layernorm(x))
                    new_k.append(kp)
                    new_v.append(vp)
                    new_ks.append(ksc)
                    new_vs.append(vsc)
                x = self.norm(x)
                if self.lm_head is not None:
                    logits = self.lm_head(x)
                else:
                    from .. import ops
                    logits = ops.matmul(x, self.embed_tokens.weight,
                                        transpose_y=True)
            last = logits._data[:, 0]
            nxt = jax.lax.cond(
                temp > 0,
                lambda: sample_token(last / jnp.maximum(temp, 1e-6),
                                     temperature=1.0, key=key),
                lambda: jnp.argmax(last, axis=-1).astype(jnp.int32))
            return nxt, new_k, new_v, new_ks, new_vs
        tag = "llama.paged_decode.q8" + (
            "" if kernel_mode == "auto" else f".k-{kernel_mode}")
        return _aot_wrap(jax.jit(fn), self._aot_tag(tag))

    # -- self-speculative decode (docs/SERVING.md "Decode speed tiers") --

    def _build_spec_jit(self, quantized):
        """The speculative VERIFY program: one batched multi-position
        sweep over every live slot. For slot ``b``, input positions
        ``seq_lens[b] + i`` carry ``toks[b, i]`` (the last emitted
        token, then the proposed drafts); each position's KV is written
        (rows past ``n_inputs[b]`` masked to the null block) and its
        query attends the whole paged context causally by absolute
        position — so ``out[b, i]`` is exactly the greedy token a
        sequential decode would emit after consuming input ``i``.
        Greedy only (the scheduler gates speculation on temperature 0);
        host-side acceptance decides how many rows survive."""
        rebind = self._param_rebind()
        cfg = self.config
        hq = cfg.num_heads
        hk = cfg.num_kv_heads
        hd = cfg.hidden_size // hq

        def fn(param_arrays, toks, lens, n_inputs, active, tables,
               k_pools, v_pools, k_scales, v_scales):
            from ..core.autograd import no_grad
            from ..inference.paged import (paged_spec_attention_dense,
                                           paged_spec_write)
            rebind(param_arrays)
            b, s = toks.shape
            with no_grad():
                x = self.embed_tokens(Tensor(toks))
                new_k, new_v, new_ks, new_vs = [], [], [], []
                for i, blk in enumerate(self.layers):
                    attn = blk.self_attn
                    h = blk.input_layernorm(x)
                    q = attn.q_proj(h).reshape([b, s, hq, hd])
                    k = attn.k_proj(h).reshape([b, s, hk, hd])
                    v = attn.v_proj(h).reshape([b, s, hk, hd])
                    q, k = apply_rope(q, k, theta=attn.rope_theta,
                                      position_offset=lens)
                    if quantized:
                        kp, vp, ksc, vsc = paged_spec_write(
                            k_pools[i], v_pools[i], tables, lens,
                            k._data, v._data, n_inputs, active,
                            k_scale=k_scales[i], v_scale=v_scales[i])
                        out = paged_spec_attention_dense(
                            q._data, kp, vp, tables, lens, active,
                            k_scale=ksc, v_scale=vsc)
                        new_ks.append(ksc)
                        new_vs.append(vsc)
                    else:
                        kp, vp = paged_spec_write(
                            k_pools[i], v_pools[i], tables, lens,
                            k._data, v._data, n_inputs, active)
                        out = paged_spec_attention_dense(
                            q._data, kp, vp, tables, lens, active)
                    x = x + attn.o_proj(
                        Tensor(out.reshape(b, s, hq * hd)))
                    x = x + blk.mlp(blk.post_attention_layernorm(x))
                    new_k.append(kp)
                    new_v.append(vp)
                x = self.norm(x)
                if self.lm_head is not None:
                    logits = self.lm_head(x)
                else:
                    from .. import ops
                    logits = ops.matmul(x, self.embed_tokens.weight,
                                        transpose_y=True)
            nxt = jnp.argmax(logits._data, axis=-1).astype(jnp.int32)
            return nxt, new_k, new_v, new_ks, new_vs
        tag = "llama.paged_spec.q8" if quantized else "llama.paged_spec"
        return _aot_wrap(jax.jit(fn), self._aot_tag(tag))

    def paged_spec_step(self, cache, last_tokens, draft_tokens, n_inputs,
                        active):
        """Speculative verify sweep: write the KV of ``1 + k``
        candidate tokens per active slot (``last_tokens[b]`` then
        ``draft_tokens[b]``) at positions ``seq_lens[b] ..`` and return
        [B, 1 + k] greedy next tokens — ``out[b, i]`` is the token
        sequential greedy decode would emit after consuming input
        ``i``. ``n_inputs[b]`` (= 1 + real drafts) masks padding
        writes. Pools update in place; ``seq_lens`` do NOT advance —
        the caller (scheduler ``_decode_spec``) accepts the longest
        matching prefix and rolls rejected rows back."""
        attr = "_paged_spec_q8_jit" if cache.quantized \
            else "_paged_spec_jit"
        if getattr(self, attr, None) is None:
            setattr(self, attr, self._build_spec_jit(cache.quantized))
        toks = np.concatenate(
            [np.asarray(last_tokens).reshape(-1, 1),
             np.asarray(draft_tokens)], axis=1)
        with self._paged_lock():
            arrs = self._param_arrays()
            nxt, nk, nv, nks, nvs = getattr(self, attr)(
                arrs, jnp.asarray(toks, jnp.int32),
                jnp.asarray(cache.seq_lens),
                jnp.asarray(n_inputs, jnp.int32),
                jnp.asarray(active), cache.block_tables,
                cache.k_pools, cache.v_pools,
                cache.k_scales if cache.quantized else [],
                cache.v_scales if cache.quantized else [])
            self._param_rebind()(arrs)
        cache.k_pools = list(nk)
        cache.v_pools = list(nv)
        if cache.quantized:
            cache.k_scales = list(nks)
            cache.v_scales = list(nvs)
        return np.asarray(nxt)

    def forward_hidden(self, input_ids, kv_sink=None):
        """Decoder stack output (post final RMSNorm), before the head."""
        x = self.embed_tokens(input_ids)
        for block in self.layers:
            x = block(x, kv_sink=kv_sink)
        return self.norm(x)

    def loss(self, input_ids, labels):
        if self.config.fused_head_ce:
            x = self.forward_hidden(input_ids)[:, :-1, :]
            tied = self.lm_head is None
            w = self.embed_tokens.weight if tied else self.lm_head.weight
            return F.fused_linear_cross_entropy(x, w, labels[:, 1:],
                                                transpose_weight=tied)
        logits = self(input_ids)
        return F.cross_entropy(logits[:, :-1, :], labels[:, 1:])

    def num_params(self):
        return sum(p.size for p in self.parameters())

    def flops_per_token(self, seq_len):
        n = self.num_params()
        l, d = self.config.num_layers, self.config.hidden_size
        return 6 * n + 12 * l * d * seq_len

    @staticmethod
    def tp_placement_rules(mesh, tp_axis="tp"):
        """Megatron-style TP placements (reference mp_layers.py:47,334,541:
        column-parallel q/k/v/gate/up, row-parallel o/down, vocab-parallel
        embedding) as rules for distributed.apply_placement_rules."""
        from ..distributed import Replicate, Shard
        axis = mesh.dim_names.index(tp_axis)

        def P(*pairs):
            pl = [Replicate()] * mesh.ndim
            for mesh_dim, tensor_dim in pairs:
                pl[mesh_dim] = Shard(tensor_dim)
            return pl

        col = P((axis, 1))   # [in, out] split out
        row = P((axis, 0))   # [in, out] split in
        return [
            ("q_proj.weight", col), ("k_proj.weight", col),
            ("v_proj.weight", col), ("gate_proj.weight", col),
            ("up_proj.weight", col),
            ("o_proj.weight", row), ("down_proj.weight", row),
            ("embed_tokens.weight", P((axis, 0))),  # vocab-parallel
            ("lm_head.weight", col),
        ]
