"""`paddle.metric` (reference: python/paddle/metric/metrics.py)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self._name = name or "acc"
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:  # one-hot / index column
            label_np = label_np.squeeze(-1) if label_np.shape[-1] == 1 \
                else label_np.argmax(-1)
        correct = (idx == label_np[..., None])
        return Tensor(correct.astype("float32"))

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0] if c.ndim > 0 else 1
        flat = c.reshape(-1, c.shape[-1])
        for i, k in enumerate(self.topk):
            self.total[i] += flat[:, :k].sum()
            self.count[i] += flat.shape[0]
        return self.accumulate()

    def accumulate(self):
        res = [t / c if c > 0 else 0.0
               for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(int).reshape(-1)
        l = _np(labels).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(int).reshape(-1)
        l = _np(labels).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = _np(labels).reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(int),
                          self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = tot_neg = area = 0.0
        for i in range(self.num_thresholds, -1, -1):
            pos, neg = self._stat_pos[i], self._stat_neg[i]
            area += neg * (tot_pos + pos + tot_pos) / 2.0
            tot_pos += pos
            tot_neg += neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional accuracy (reference paddle.metric.accuracy)."""
    pred = _np(input)
    lab = _np(label).reshape(-1)
    topk = np.argsort(-pred, axis=-1)[:, :k]
    correct_mask = (topk == lab[:, None]).any(axis=1)
    return Tensor(np.asarray(correct_mask.mean(), np.float32))
