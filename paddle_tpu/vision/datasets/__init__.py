"""`paddle.vision.datasets` (reference: python/paddle/vision/datasets/).

Download-backed datasets (MNIST/FashionMNIST/Cifar) cache under
~/.cache/paddle_tpu/dataset; FakeData generates synthetic samples for
tests/CI (reference uses the same pattern in test/legacy_test)."""

from __future__ import annotations

import gzip
import os
import pickle
import tarfile
import urllib.request

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers",
           "VOC2012", "FakeData", "DatasetFolder", "ImageFolder"]

_HOME = os.path.expanduser(os.environ.get(
    "PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def _fetch(url, path):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if not os.path.exists(path):
        urllib.request.urlretrieve(url, path)
    return path


class FakeData(Dataset):
    """Synthetic images (for tests — no download)."""

    def __init__(self, size=100, image_shape=(3, 32, 32), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.RandomState(seed)
        self._images = self._rng.randint(
            0, 256, (size,) + self.image_shape).astype("uint8")
        self._labels = self._rng.randint(0, num_classes, size).astype(
            "int64")

    def __getitem__(self, idx):
        img = self._images[idx]
        if self.transform is not None:
            img = self.transform(np.transpose(img, (1, 2, 0)))
        else:
            img = img.astype("float32") / 255.0
        return img, self._labels[idx]

    def __len__(self):
        return self.size


class MNIST(Dataset):
    URL = "https://storage.googleapis.com/cvdf-datasets/mnist/"
    FILES = {
        "train": ("train-images-idx3-ubyte.gz",
                  "train-labels-idx1-ubyte.gz"),
        "test": ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"),
    }
    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        assert mode in ("train", "test")
        self.transform = transform
        img_file, lab_file = self.FILES[mode]
        root = os.path.join(_HOME, self.NAME)
        image_path = image_path or os.path.join(root, img_file)
        label_path = label_path or os.path.join(root, lab_file)
        if download and not os.path.exists(image_path):
            _fetch(self.URL + img_file, image_path)
            _fetch(self.URL + lab_file, label_path)
        with gzip.open(image_path, "rb") as f:
            data = np.frombuffer(f.read(), np.uint8, offset=16)
        self.images = data.reshape(-1, 28, 28)
        with gzip.open(label_path, "rb") as f:
            self.labels = np.frombuffer(f.read(), np.uint8,
                                        offset=8).astype("int64")

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = (img.astype("float32") / 255.0)[None]
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    URL = "http://fashion-mnist.s3-website.eu-central-1.amazonaws.com/"
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
    NAME = "cifar-10-batches-py"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        assert mode in ("train", "test")
        self.transform = transform
        root = _HOME
        archive = data_file or os.path.join(root, "cifar-10-python.tar.gz")
        folder = os.path.join(root, self.NAME)
        if not os.path.isdir(folder):
            # a user-supplied data_file is extracted, NEVER re-fetched
            # over (reference cifar.py honors the local archive)
            if not os.path.exists(archive):
                if not download:
                    raise RuntimeError(f"{archive} missing and "
                                       "download=False")
                _fetch(self.URL, archive)
            os.makedirs(root, exist_ok=True)
            with tarfile.open(archive) as tf:
                tf.extractall(root, filter="data")
        batches = [f"data_batch_{i}" for i in range(1, 6)] \
            if mode == "train" else ["test_batch"]
        xs, ys = [], []
        for b in batches:
            with open(os.path.join(folder, b), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.extend(d[b"labels"])
        self.images = np.concatenate(xs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(ys, "int64")

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(np.transpose(img, (1, 2, 0)))
        else:
            img = img.astype("float32") / 255.0
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    URL = "https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz"
    NAME = "cifar-100-python"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        root = _HOME
        archive = data_file or os.path.join(root, "cifar-100-python.tar.gz")
        folder = os.path.join(root, self.NAME)
        if not os.path.isdir(folder):
            if not os.path.exists(archive):
                if not download:
                    raise RuntimeError(f"{archive} missing and "
                                       "download=False")
                _fetch(self.URL, archive)
            os.makedirs(root, exist_ok=True)
            with tarfile.open(archive) as tf:
                tf.extractall(root, filter="data")
        fname = "train" if mode == "train" else "test"
        with open(os.path.join(folder, fname), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        self.images = d[b"data"].reshape(-1, 3, 32, 32)
        self.labels = np.asarray(d[b"fine_labels"], "int64")


class Flowers(Dataset):
    """Flowers102 (reference python/paddle/vision/datasets/flowers.py:54):
    102flowers.tgz images + imagelabels.mat / setid.mat splits."""

    DATA_URL = "http://paddlemodels.bj.bcebos.com/flowers/102flowers.tgz"
    LABEL_URL = "http://paddlemodels.bj.bcebos.com/flowers/imagelabels.mat"
    SETID_URL = "http://paddlemodels.bj.bcebos.com/flowers/setid.mat"
    # the reference swaps train/test on purpose (flowers.py:48-51: the
    # official tstid split is larger, so it serves as training data)
    _FLAG = {"train": "tstid", "valid": "valid", "test": "trnid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        assert mode in self._FLAG, mode
        self.transform = transform
        root = os.path.join(_HOME, "flowers")
        data_file = data_file or os.path.join(root, "102flowers.tgz")
        label_file = label_file or os.path.join(root, "imagelabels.mat")
        setid_file = setid_file or os.path.join(root, "setid.mat")
        if download:
            for url, path in ((self.DATA_URL, data_file),
                              (self.LABEL_URL, label_file),
                              (self.SETID_URL, setid_file)):
                if not os.path.exists(path):
                    _fetch(url, path)
        import scipy.io as scio
        self._tar = tarfile.open(data_file)
        self._names = {os.path.basename(n): n
                       for n in self._tar.getnames()
                       if n.endswith(".jpg")}
        self.labels = scio.loadmat(label_file)["labels"][0]
        self.indexes = scio.loadmat(setid_file)[self._FLAG[mode]][0]

    def __getitem__(self, idx):
        index = int(self.indexes[idx])
        label = np.array([int(self.labels[index - 1])], dtype="int64")
        fname = "image_%05d.jpg" % index
        data = self._tar.extractfile(self._names[fname]).read()
        import io as _io

        from PIL import Image
        img = np.asarray(Image.open(_io.BytesIO(data)).convert("RGB"))
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """VOC2012 segmentation pairs (reference
    python/paddle/vision/datasets/voc2012.py:54): (image, label-mask)
    from VOCtrainval_11-May-2012.tar."""

    VOC_URL = ("https://dataset.bj.bcebos.com/voc/"
               "VOCtrainval_11-May-2012.tar")
    # reference MODE_FLAG_MAP (voc2012.py:51): train->trainval,
    # test->train, valid->val
    _SETS = {"train": "trainval.txt", "valid": "val.txt",
             "test": "train.txt"}
    _PREFIX = "VOCdevkit/VOC2012"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        assert mode in self._SETS, mode
        self.transform = transform
        data_file = data_file or os.path.join(
            _HOME, "voc2012", "VOCtrainval_11-May-2012.tar")
        if download and not os.path.exists(data_file):
            _fetch(self.VOC_URL, data_file)
        self._tar = tarfile.open(data_file)
        lst = self._tar.extractfile(
            f"{self._PREFIX}/ImageSets/Segmentation/"
            f"{self._SETS[mode]}").read().decode()
        self.keys = [k for k in lst.split() if k]

    def __getitem__(self, idx):
        import io as _io

        from PIL import Image
        key = self.keys[idx]
        img = np.asarray(Image.open(_io.BytesIO(self._tar.extractfile(
            f"{self._PREFIX}/JPEGImages/{key}.jpg").read()))
            .convert("RGB"))
        label = np.asarray(Image.open(_io.BytesIO(self._tar.extractfile(
            f"{self._PREFIX}/SegmentationClass/{key}.png").read())))
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.keys)


_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".npy")


class DatasetFolder(Dataset):
    """class-per-subfolder image dataset (reference folder.py)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or _IMG_EXTS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if is_valid_file is not None:
                    if is_valid_file(fn):
                        self.samples.append((os.path.join(cdir, fn),
                                             self.class_to_idx[c]))
                elif fn.lower().endswith(tuple(extensions)):
                    self.samples.append((os.path.join(cdir, fn),
                                         self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError as e:
            raise RuntimeError(
                "loading non-.npy images requires pillow") from e

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    """flat (unlabeled) image folder."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        extensions = extensions or _IMG_EXTS
        self.samples = [os.path.join(root, fn)
                        for fn in sorted(os.listdir(root))
                        if fn.lower().endswith(tuple(extensions))]

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return (img,)

    def __len__(self):
        return len(self.samples)
