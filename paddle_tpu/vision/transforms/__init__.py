"""`paddle.vision.transforms` (reference: python/paddle/vision/
transforms/)."""

from .transforms import (  # noqa: F401
    BaseTransform, BrightnessTransform, CenterCrop, ColorJitter, Compose,
    ContrastTransform, Grayscale, HueTransform, Normalize, Pad,
    RandomAffine, RandomCrop, RandomErasing, RandomHorizontalFlip,
    RandomPerspective, RandomResizedCrop, RandomRotation,
    RandomVerticalFlip, Resize, SaturationTransform, ToTensor, Transpose,
)
from .functional import (  # noqa: F401
    adjust_brightness, adjust_contrast, adjust_hue, adjust_saturation,
    affine, center_crop, crop, erase, hflip, normalize, pad, perspective,
    resize, rotate, to_grayscale, to_tensor, vflip,
)
from . import functional  # noqa: F401
