"""`paddle.vision.transforms` (reference: python/paddle/vision/
transforms/)."""

from .transforms import (  # noqa: F401
    BaseTransform, BrightnessTransform, CenterCrop, ColorJitter, Compose,
    ContrastTransform, Grayscale, Normalize, Pad, RandomCrop,
    RandomHorizontalFlip, RandomResizedCrop, RandomRotation,
    RandomVerticalFlip, Resize, ToTensor, Transpose,
)
from . import functional  # noqa: F401
