"""Image transform functionals on numpy HWC arrays (reference:
python/paddle/vision/transforms/functional*.py — we standardize on the
'cv2'-style numpy backend; PIL objects are converted on entry)."""

from __future__ import annotations

import numbers

import numpy as np

from ...core.tensor import Tensor


def _to_numpy(img):
    if isinstance(img, np.ndarray):
        return img
    if isinstance(img, Tensor):
        return img.numpy()
    # PIL image
    return np.asarray(img)


def to_tensor(pic, data_format="CHW"):
    arr = _to_numpy(pic)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.dtype == np.uint8:
        arr = arr.astype("float32") / 255.0
    else:
        arr = arr.astype("float32")
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return Tensor(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    t = img
    arr = t.numpy() if isinstance(t, Tensor) else _to_numpy(t).astype(
        "float32")
    mean = np.asarray(mean, "float32")
    std = np.asarray(std, "float32")
    if data_format == "CHW":
        arr = (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


def _interp_resize(arr, h, w):
    """Bilinear resize without external deps."""
    ih, iw = arr.shape[:2]
    if (ih, iw) == (h, w):
        return arr
    ys = (np.arange(h) + 0.5) * ih / h - 0.5
    xs = (np.arange(w) + 0.5) * iw / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, ih - 1)
    y1 = np.clip(y0 + 1, 0, ih - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, iw - 1)
    x1 = np.clip(x0 + 1, 0, iw - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None]
    wx = np.clip(xs - x0, 0, 1)[None, :]
    a = arr[y0][:, x0]
    b = arr[y0][:, x1]
    c = arr[y1][:, x0]
    d = arr[y1][:, x1]
    if arr.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    top = a * (1 - wx) + b * wx
    bot = c * (1 - wx) + d * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(arr.dtype) if arr.dtype != np.uint8 else \
        np.clip(out, 0, 255).astype(np.uint8)


def resize(img, size, interpolation="bilinear"):
    arr = _to_numpy(img)
    if isinstance(size, numbers.Number):
        h, w = arr.shape[:2]
        if h <= w:
            new_h, new_w = int(size), int(size * w / h)
        else:
            new_h, new_w = int(size * h / w), int(size)
    else:
        new_h, new_w = size
    return _interp_resize(arr, new_h, new_w)


def crop(img, top, left, height, width):
    arr = _to_numpy(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _to_numpy(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = arr.shape[:2]
    th, tw = output_size
    top = max((h - th) // 2, 0)
    left = max((w - tw) // 2, 0)
    return crop(arr, top, left, th, tw)


def hflip(img):
    return _to_numpy(img)[:, ::-1].copy()


def vflip(img):
    return _to_numpy(img)[::-1].copy()


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _to_numpy(img)
    if isinstance(padding, numbers.Number):
        padding = [padding] * 4
    if len(padding) == 2:
        padding = [padding[0], padding[1], padding[0], padding[1]]
    left, top, right, bottom = padding
    pads = [(top, bottom), (left, right)] + \
        [(0, 0)] * (arr.ndim - 2)
    if padding_mode == "constant":
        return np.pad(arr, pads, constant_values=fill)
    return np.pad(arr, pads, mode=padding_mode)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    arr = _to_numpy(img)
    h, w = arr.shape[:2]
    cy, cx = (h / 2, w / 2) if center is None else (center[1], center[0])
    rad = -np.deg2rad(angle)
    cos_a, sin_a = np.cos(rad), np.sin(rad)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ys = cos_a * (yy - cy) - sin_a * (xx - cx) + cy
    xs = sin_a * (yy - cy) + cos_a * (xx - cx) + cx
    yi = np.clip(np.round(ys).astype(int), 0, h - 1)
    xi = np.clip(np.round(xs).astype(int), 0, w - 1)
    out = arr[yi, xi]
    inside = (ys >= 0) & (ys < h) & (xs >= 0) & (xs < w)
    if arr.ndim == 3:
        inside = inside[..., None]
    return np.where(inside, out, fill).astype(arr.dtype)


def adjust_brightness(img, factor):
    arr = _to_numpy(img).astype("float32") * factor
    return np.clip(arr, 0, 255).astype("uint8") \
        if _to_numpy(img).dtype == np.uint8 else arr


def adjust_contrast(img, factor):
    arr = _to_numpy(img).astype("float32")
    mean = arr.mean()
    out = (arr - mean) * factor + mean
    return np.clip(out, 0, 255).astype("uint8") \
        if _to_numpy(img).dtype == np.uint8 else out


def to_grayscale(img, num_output_channels=1):
    arr = _to_numpy(img).astype("float32")
    if arr.ndim == 2:
        g = arr
    else:
        g = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    g = g[..., None]
    if num_output_channels == 3:
        g = np.repeat(g, 3, axis=-1)
    return g.astype(_to_numpy(img).dtype)
