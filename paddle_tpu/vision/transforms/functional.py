"""Image transform functionals on numpy HWC arrays (reference:
python/paddle/vision/transforms/functional*.py — we standardize on the
'cv2'-style numpy backend; PIL objects are converted on entry)."""

from __future__ import annotations

import numbers

import numpy as np

from ...core.tensor import Tensor


def _to_numpy(img):
    if isinstance(img, np.ndarray):
        return img
    if isinstance(img, Tensor):
        return img.numpy()
    # PIL image
    return np.asarray(img)


def to_tensor(pic, data_format="CHW"):
    arr = _to_numpy(pic)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.dtype == np.uint8:
        arr = arr.astype("float32") / 255.0
    else:
        arr = arr.astype("float32")
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return Tensor(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    t = img
    arr = t.numpy() if isinstance(t, Tensor) else _to_numpy(t).astype(
        "float32")
    mean = np.asarray(mean, "float32")
    std = np.asarray(std, "float32")
    if data_format == "CHW":
        arr = (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


def _interp_resize(arr, h, w):
    """Bilinear resize without external deps."""
    ih, iw = arr.shape[:2]
    if (ih, iw) == (h, w):
        return arr
    ys = (np.arange(h) + 0.5) * ih / h - 0.5
    xs = (np.arange(w) + 0.5) * iw / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, ih - 1)
    y1 = np.clip(y0 + 1, 0, ih - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, iw - 1)
    x1 = np.clip(x0 + 1, 0, iw - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None]
    wx = np.clip(xs - x0, 0, 1)[None, :]
    a = arr[y0][:, x0]
    b = arr[y0][:, x1]
    c = arr[y1][:, x0]
    d = arr[y1][:, x1]
    if arr.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    top = a * (1 - wx) + b * wx
    bot = c * (1 - wx) + d * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(arr.dtype) if arr.dtype != np.uint8 else \
        np.clip(out, 0, 255).astype(np.uint8)


def resize(img, size, interpolation="bilinear"):
    arr = _to_numpy(img)
    if isinstance(size, numbers.Number):
        h, w = arr.shape[:2]
        if h <= w:
            new_h, new_w = int(size), int(size * w / h)
        else:
            new_h, new_w = int(size * h / w), int(size)
    else:
        new_h, new_w = size
    return _interp_resize(arr, new_h, new_w)


def crop(img, top, left, height, width):
    arr = _to_numpy(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _to_numpy(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = arr.shape[:2]
    th, tw = output_size
    top = max((h - th) // 2, 0)
    left = max((w - tw) // 2, 0)
    return crop(arr, top, left, th, tw)


def hflip(img):
    return _to_numpy(img)[:, ::-1].copy()


def vflip(img):
    return _to_numpy(img)[::-1].copy()


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _to_numpy(img)
    if isinstance(padding, numbers.Number):
        padding = [padding] * 4
    if len(padding) == 2:
        padding = [padding[0], padding[1], padding[0], padding[1]]
    left, top, right, bottom = padding
    pads = [(top, bottom), (left, right)] + \
        [(0, 0)] * (arr.ndim - 2)
    if padding_mode == "constant":
        return np.pad(arr, pads, constant_values=fill)
    return np.pad(arr, pads, mode=padding_mode)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    arr = _to_numpy(img)
    h, w = arr.shape[:2]
    cy, cx = (h / 2, w / 2) if center is None else (center[1], center[0])
    rad = -np.deg2rad(angle)
    cos_a, sin_a = np.cos(rad), np.sin(rad)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ys = cos_a * (yy - cy) - sin_a * (xx - cx) + cy
    xs = sin_a * (yy - cy) + cos_a * (xx - cx) + cx
    yi = np.clip(np.round(ys).astype(int), 0, h - 1)
    xi = np.clip(np.round(xs).astype(int), 0, w - 1)
    out = arr[yi, xi]
    inside = (ys >= 0) & (ys < h) & (xs >= 0) & (xs < w)
    if arr.ndim == 3:
        inside = inside[..., None]
    return np.where(inside, out, fill).astype(arr.dtype)


def adjust_brightness(img, factor):
    arr = _to_numpy(img).astype("float32") * factor
    return np.clip(arr, 0, 255).astype("uint8") \
        if _to_numpy(img).dtype == np.uint8 else arr


def adjust_contrast(img, factor):
    arr = _to_numpy(img).astype("float32")
    mean = arr.mean()
    out = (arr - mean) * factor + mean
    return np.clip(out, 0, 255).astype("uint8") \
        if _to_numpy(img).dtype == np.uint8 else out


def to_grayscale(img, num_output_channels=1):
    arr = _to_numpy(img).astype("float32")
    if arr.ndim == 2:
        g = arr
    else:
        g = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    g = g[..., None]
    if num_output_channels == 3:
        g = np.repeat(g, 3, axis=-1)
    return g.astype(_to_numpy(img).dtype)


def adjust_saturation(img, factor):
    """Blend with the grayscale image (reference adjust_saturation)."""
    arr = _to_numpy(img).astype("float32")
    g = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    out = arr * factor + g[..., None] * (1 - factor)
    return np.clip(out, 0, 255).astype("uint8") \
        if _to_numpy(img).dtype == np.uint8 else out


def adjust_hue(img, hue_factor):
    """Shift hue in HSV space by hue_factor (in [-0.5, 0.5]; reference
    adjust_hue)."""
    arr = _to_numpy(img).astype("float32")
    was_uint8 = _to_numpy(img).dtype == np.uint8
    x = arr / 255.0 if was_uint8 else arr
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    maxc = np.maximum(np.maximum(r, g), b)
    minc = np.minimum(np.minimum(r, g), b)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(delta, 1e-12)
    h = np.where(
        maxc == r, ((g - b) / dz) % 6,
        np.where(maxc == g, (b - r) / dz + 2, (r - g) / dz + 4)) / 6.0
    h = np.where(delta == 0, 0.0, h)
    h = (h + hue_factor) % 1.0
    # hsv -> rgb
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = (i.astype(int) % 6)[..., None]
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    if was_uint8:
        return np.clip(out * 255.0, 0, 255).astype("uint8")
    return out


def _sample_affine(arr, matrix, interpolation="nearest", fill=0):
    """Inverse-warp sampling with a 2x3 (or 3x3) matrix mapping OUTPUT
    pixel coords to INPUT coords."""
    h, w = arr.shape[:2]
    m = np.asarray(matrix, "float64").reshape(-1)
    if m.size == 6:
        m = np.concatenate([m, [0, 0, 1]])
    m = m.reshape(3, 3)
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1).astype("float64")
    src = m @ coords
    sx = src[0] / np.maximum(np.abs(src[2]), 1e-12) * np.sign(src[2])
    sy = src[1] / np.maximum(np.abs(src[2]), 1e-12) * np.sign(src[2])
    eps = 1e-4  # numerical slack so exact borders stay inside
    valid = (sx >= -eps) & (sx <= w - 1 + eps) & \
        (sy >= -eps) & (sy <= h - 1 + eps)
    sx = np.clip(sx, 0, w - 1)
    sy = np.clip(sy, 0, h - 1)
    if interpolation == "bilinear":
        x0 = np.clip(np.floor(sx).astype(int), 0, w - 1)
        y0 = np.clip(np.floor(sy).astype(int), 0, h - 1)
        x1 = np.clip(x0 + 1, 0, w - 1)
        y1 = np.clip(y0 + 1, 0, h - 1)
        wx = (sx - x0)[..., None]
        wy = (sy - y0)[..., None]
        a2 = arr.reshape(h, w, -1).astype("float32")
        out = (a2[y0, x0] * (1 - wx) * (1 - wy) + a2[y0, x1] * wx * (1 - wy)
               + a2[y1, x0] * (1 - wx) * wy + a2[y1, x1] * wx * wy)
    else:
        ix = np.clip(np.round(sx).astype(int), 0, w - 1)
        iy = np.clip(np.round(sy).astype(int), 0, h - 1)
        out = arr.reshape(h, w, -1)[iy, ix].astype("float32")
    out = np.where(valid[:, None], out, np.float32(fill))
    out = out.reshape(h, w, *arr.shape[2:])
    return np.clip(out, 0, 255).astype("uint8") \
        if arr.dtype == np.uint8 else out.astype(arr.dtype)


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="nearest", fill=0, center=None):
    """Affine warp (reference transforms.functional.affine)."""
    arr = _to_numpy(img)
    h, w = arr.shape[:2]
    cx, cy = center if center is not None else ((w - 1) / 2, (h - 1) / 2)
    rot = np.deg2rad(angle)
    sx, sy = [np.deg2rad(s) for s in (
        shear if isinstance(shear, (list, tuple)) else (shear, 0.0))]
    # forward matrix: T(center) R S Shear T(-center) T(translate)
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    fwd = np.array([[a * scale, b * scale, 0],
                    [c * scale, d * scale, 0],
                    [0, 0, 1]], "float32")
    t_c = np.array([[1, 0, cx + translate[0]], [0, 1, cy + translate[1]],
                    [0, 0, 1]], "float32")
    t_nc = np.array([[1, 0, -cx], [0, 1, -cy], [0, 0, 1]], "float32")
    m = t_c @ fwd @ t_nc
    inv = np.linalg.inv(m)
    return _sample_affine(arr, inv, interpolation, fill)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective warp mapping startpoints -> endpoints (reference
    transforms.functional.perspective)."""
    arr = _to_numpy(img)
    src = np.asarray(startpoints, "float32")
    dst = np.asarray(endpoints, "float32")
    # solve homography dst -> src (inverse warp)
    A = []
    for (xs, ys), (xd, yd) in zip(src, dst):
        A.append([xd, yd, 1, 0, 0, 0, -xs * xd, -xs * yd, -xs])
        A.append([0, 0, 0, xd, yd, 1, -ys * xd, -ys * yd, -ys])
    A = np.asarray(A, "float64")
    _, _, vt = np.linalg.svd(A)
    m = vt[-1].reshape(3, 3)
    m = m / m[2, 2]
    return _sample_affine(arr, m, interpolation, fill)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase a region with value v (reference functional.erase)."""
    if isinstance(img, Tensor):
        arr = img.numpy().copy()
        arr[..., i:i + h, j:j + w] = v
        return Tensor(arr)
    arr = _to_numpy(img).copy()
    if arr.ndim == 3:  # HWC
        arr[i:i + h, j:j + w, :] = v
    else:
        arr[i:i + h, j:j + w] = v
    return arr
