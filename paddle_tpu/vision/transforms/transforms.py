"""Transform classes (reference: python/paddle/vision/transforms/
transforms.py)."""

from __future__ import annotations

import numbers
import random

import numpy as np

from . import functional as F


from . import functional as functional_mod


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = F._to_numpy(img)
        if self.padding is not None:
            arr = F.pad(arr, self.padding, self.fill, self.padding_mode)
        h, w = arr.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            arr = F.pad(arr, [max(tw - w, 0), max(th - h, 0)], self.fill,
                        self.padding_mode)
            h, w = arr.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(arr, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return F.hflip(img)
        return F._to_numpy(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return F.vflip(img)
        return F._to_numpy(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = F._to_numpy(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * random.uniform(*self.scale)
            aspect = random.uniform(*self.ratio)
            tw = int(round(np.sqrt(target_area * aspect)))
            th = int(round(np.sqrt(target_area / aspect)))
            if th <= h and tw <= w:
                top = random.randint(0, h - th)
                left = random.randint(0, w - tw)
                cropped = F.crop(arr, top, left, th, tw)
                return F.resize(cropped, self.size, self.interpolation)
        return F.resize(F.center_crop(arr, min(h, w)), self.size,
                        self.interpolation)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.kw = dict(interpolation=interpolation, expand=expand,
                       center=center, fill=fill)

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, **self.kw)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = F._to_numpy(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.brightness = brightness
        self.contrast = contrast

    def _apply_image(self, img):
        out = img
        if self.brightness:
            out = BrightnessTransform(self.brightness)._apply_image(out)
        if self.contrast:
            out = ContrastTransform(self.contrast)._apply_image(out)
        return F._to_numpy(out)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__()
        self.value = value

    def _apply_image(self, img):
        import random
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return functional_mod.adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__()
        self.value = min(max(value, 0.0), 0.5)

    def _apply_image(self, img):
        import random
        f = random.uniform(-self.value, self.value)
        return functional_mod.adjust_hue(img, f)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__()
        self.degrees = degrees if isinstance(degrees, (list, tuple)) \
            else (-degrees, degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        import random
        angle = random.uniform(*self.degrees)
        h, w = functional_mod._to_numpy(img).shape[:2]
        tx = ty = 0
        if self.translate is not None:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
        sc = random.uniform(*self.scale) if self.scale else 1.0
        sh = (random.uniform(-self.shear, self.shear)
              if isinstance(self.shear, (int, float)) and self.shear
              else 0.0)
        return functional_mod.affine(
            img, angle=angle, translate=(tx, ty), scale=sc,
            shear=(sh, 0.0), interpolation=self.interpolation,
            fill=self.fill, center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__()
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        import random
        if random.random() >= self.prob:
            return img
        h, w = functional_mod._to_numpy(img).shape[:2]
        d = self.distortion_scale
        hw = int(w * d / 2)
        hh = int(h * d / 2)

        def jig(x, y):
            return (x + random.randint(-hw, hw) if hw else x,
                    y + random.randint(-hh, hh) if hh else y)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [jig(*p) for p in start]
        return functional_mod.perspective(img, start, end,
                                          self.interpolation, self.fill)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__()
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        import math
        import random
        if random.random() >= self.prob:
            return img
        arr = functional_mod._to_numpy(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = math.exp(random.uniform(math.log(self.ratio[0]),
                                         math.log(self.ratio[1])))
            eh = int(round(math.sqrt(target * ar)))
            ew = int(round(math.sqrt(target / ar)))
            if eh < h and ew < w:
                i = random.randint(0, h - eh)
                j = random.randint(0, w - ew)
                return functional_mod.erase(img, i, j, eh, ew, self.value)
        return img
