"""GoogLeNet / Inception-v1 (reference: python/paddle/vision/models/
googlenet.py API — forward returns (out, aux1, aux2) like the
reference)."""

from __future__ import annotations

from ... import nn, ops

__all__ = ["GoogLeNet", "googlenet"]


def _conv_bn(in_ch, out_ch, k, stride=1, padding=0):
    return nn.Sequential(
        nn.Conv2D(in_ch, out_ch, k, stride=stride, padding=padding),
        nn.BatchNorm2D(out_ch), nn.ReLU())


class _Inception(nn.Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, pp):
        super().__init__()
        self.b1 = _conv_bn(in_ch, c1, 1)
        self.b2 = nn.Sequential(_conv_bn(in_ch, c3r, 1),
                                _conv_bn(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_conv_bn(in_ch, c5r, 1),
                                _conv_bn(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, 1),
                                _conv_bn(in_ch, pp, 1))

    def forward(self, x):
        return ops.concat([self.b1(x), self.b2(x), self.b3(x),
                           self.b4(x)], axis=1)


class _AuxHead(nn.Layer):
    def __init__(self, in_ch, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(4)
        self.conv = _conv_bn(in_ch, 128, 1)
        self.fc1 = nn.Linear(128 * 16, 1024)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.conv(self.pool(x))
        x = self.relu(self.fc1(ops.flatten(x, 1)))
        return self.fc2(self.dropout(x))


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _conv_bn(3, 64, 7, stride=2, padding=3), nn.MaxPool2D(3, 2, 1),
            _conv_bn(64, 64, 1), _conv_bn(64, 192, 3, padding=1),
            nn.MaxPool2D(3, 2, 1))
        self.inc3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, 1)
        self.inc4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, 1)
        self.inc5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.aux1 = _AuxHead(512, num_classes)
        self.aux2 = _AuxHead(528, num_classes)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.4)
        if num_classes > 0:
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.inc3b(self.inc3a(x)))
        x = self.inc4a(x)
        a1 = self.aux1(x)
        x = self.inc4d(self.inc4c(self.inc4b(x)))
        a2 = self.aux2(x)
        x = self.pool4(self.inc4e(x))
        x = self.inc5b(self.inc5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        x = self.dropout(x)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x, a1, a2


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)
