"""SqueezeNet 1.0/1.1 (reference: python/paddle/vision/models/
squeezenet.py API)."""

from __future__ import annotations

from ... import nn, ops

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class _Fire(nn.Layer):
    def __init__(self, in_ch, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_ch, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        s = self.relu(self.squeeze(x))
        return ops.concat([self.relu(self.expand1(s)),
                           self.relu(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        relu = nn.ReLU()
        pool = lambda: nn.MaxPool2D(3, 2, ceil_mode=True)  # noqa: E731
        if version == "1.0":
            feats = [nn.Conv2D(3, 96, 7, stride=2), relu, pool(),
                     _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                     _Fire(128, 32, 128, 128), pool(),
                     _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                     _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                     pool(), _Fire(512, 64, 256, 256)]
        else:
            feats = [nn.Conv2D(3, 64, 3, stride=2), relu, pool(),
                     _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64), pool(),
                     _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                     pool(), _Fire(256, 48, 192, 192),
                     _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                     _Fire(512, 64, 256, 256)]
        self.features = nn.Sequential(*feats)
        self.dropout = nn.Dropout(0.5)
        self.final_conv = nn.Conv2D(512, num_classes, 1)
        self.relu = nn.ReLU()
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.features(x)
        x = self.relu(self.final_conv(self.dropout(x)))
        if self.with_pool:
            x = self.avgpool(x)
        return ops.flatten(x, 1)


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet("1.1", **kwargs)
