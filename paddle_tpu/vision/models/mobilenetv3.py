"""MobileNetV3 small/large (reference: python/paddle/vision/models/
mobilenetv3.py API): inverted residuals + squeeze-excite + hardswish."""

from __future__ import annotations

from ... import nn, ops
from ...nn import functional as F

__all__ = ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _SqueezeExcite(nn.Layer):
    def __init__(self, ch, squeeze_ch):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, squeeze_ch, 1)
        self.fc2 = nn.Conv2D(squeeze_ch, ch, 1)
        self.relu = nn.ReLU()

    def forward(self, x):
        s = self.relu(self.fc1(self.pool(x)))
        return x * F.hardsigmoid(self.fc2(s))


class _InvertedResidual(nn.Layer):
    def __init__(self, in_ch, exp_ch, out_ch, k, stride, use_se, use_hs):
        super().__init__()
        self.use_res = stride == 1 and in_ch == out_ch
        act = nn.Hardswish() if use_hs else nn.ReLU()
        layers = []
        if exp_ch != in_ch:
            layers += [nn.Conv2D(in_ch, exp_ch, 1, bias_attr=False),
                       nn.BatchNorm2D(exp_ch), act]
        layers += [nn.Conv2D(exp_ch, exp_ch, k, stride=stride,
                             padding=k // 2, groups=exp_ch,
                             bias_attr=False),
                   nn.BatchNorm2D(exp_ch), act]
        if use_se:
            layers.append(_SqueezeExcite(exp_ch,
                                         _make_divisible(exp_ch // 4)))
        layers += [nn.Conv2D(exp_ch, out_ch, 1, bias_attr=False),
                   nn.BatchNorm2D(out_ch)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# (kernel, expanded, out, use_se, use_hs, stride)
_LARGE = [
    (3, 16, 16, False, False, 1), (3, 64, 24, False, False, 2),
    (3, 72, 24, False, False, 1), (5, 72, 40, True, False, 2),
    (5, 120, 40, True, False, 1), (5, 120, 40, True, False, 1),
    (3, 240, 80, False, True, 2), (3, 200, 80, False, True, 1),
    (3, 184, 80, False, True, 1), (3, 184, 80, False, True, 1),
    (3, 480, 112, True, True, 1), (3, 672, 112, True, True, 1),
    (5, 672, 160, True, True, 2), (5, 960, 160, True, True, 1),
    (5, 960, 160, True, True, 1),
]
_SMALL = [
    (3, 16, 16, True, False, 2), (3, 72, 24, False, False, 2),
    (3, 88, 24, False, False, 1), (5, 96, 40, True, True, 2),
    (5, 240, 40, True, True, 1), (5, 240, 40, True, True, 1),
    (5, 120, 48, True, True, 1), (5, 144, 48, True, True, 1),
    (5, 288, 96, True, True, 2), (5, 576, 96, True, True, 1),
    (5, 576, 96, True, True, 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_ch, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        ch = _make_divisible(16 * scale)
        layers = [nn.Conv2D(3, ch, 3, stride=2, padding=1,
                            bias_attr=False),
                  nn.BatchNorm2D(ch), nn.Hardswish()]
        for k, exp, out, se, hs, s in cfg:
            exp_ch = _make_divisible(exp * scale)
            out_ch = _make_divisible(out * scale)
            layers.append(_InvertedResidual(ch, exp_ch, out_ch, k, s,
                                            se, hs))
            ch = out_ch
        final = _make_divisible(cfg[-1][1] * scale)
        layers += [nn.Conv2D(ch, final, 1, bias_attr=False),
                   nn.BatchNorm2D(final), nn.Hardswish()]
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(final, last_ch), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(ops.flatten(x, 1))
        return x


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 1024, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
