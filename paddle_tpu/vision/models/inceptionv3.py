"""Inception-v3 (reference: python/paddle/vision/models/inceptionv3.py
API)."""

from __future__ import annotations

from ... import nn, ops

__all__ = ["InceptionV3", "inception_v3"]


def _conv_bn(in_ch, out_ch, k, stride=1, padding=0):
    return nn.Sequential(
        nn.Conv2D(in_ch, out_ch, k, stride=stride, padding=padding,
                  bias_attr=False),
        nn.BatchNorm2D(out_ch), nn.ReLU())


class _InceptionA(nn.Layer):
    def __init__(self, in_ch, pool_ch):
        super().__init__()
        self.b1 = _conv_bn(in_ch, 64, 1)
        self.b5 = nn.Sequential(_conv_bn(in_ch, 48, 1),
                                _conv_bn(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_conv_bn(in_ch, 64, 1),
                                _conv_bn(64, 96, 3, padding=1),
                                _conv_bn(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, 1),
                                _conv_bn(in_ch, pool_ch, 1))

    def forward(self, x):
        return ops.concat([self.b1(x), self.b5(x), self.b3(x),
                           self.bp(x)], axis=1)


class _InceptionB(nn.Layer):  # grid reduction 35->17
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = _conv_bn(in_ch, 384, 3, stride=2)
        self.b3d = nn.Sequential(_conv_bn(in_ch, 64, 1),
                                 _conv_bn(64, 96, 3, padding=1),
                                 _conv_bn(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return ops.concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class _InceptionC(nn.Layer):  # 17x17 factorized 7x7
    def __init__(self, in_ch, ch7):
        super().__init__()
        self.b1 = _conv_bn(in_ch, 192, 1)
        self.b7 = nn.Sequential(
            _conv_bn(in_ch, ch7, 1),
            _conv_bn(ch7, ch7, (1, 7), padding=(0, 3)),
            _conv_bn(ch7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _conv_bn(in_ch, ch7, 1),
            _conv_bn(ch7, ch7, (7, 1), padding=(3, 0)),
            _conv_bn(ch7, ch7, (1, 7), padding=(0, 3)),
            _conv_bn(ch7, ch7, (7, 1), padding=(3, 0)),
            _conv_bn(ch7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, 1),
                                _conv_bn(in_ch, 192, 1))

    def forward(self, x):
        return ops.concat([self.b1(x), self.b7(x), self.b7d(x),
                           self.bp(x)], axis=1)


class _InceptionD(nn.Layer):  # grid reduction 17->8
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = nn.Sequential(_conv_bn(in_ch, 192, 1),
                                _conv_bn(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _conv_bn(in_ch, 192, 1),
            _conv_bn(192, 192, (1, 7), padding=(0, 3)),
            _conv_bn(192, 192, (7, 1), padding=(3, 0)),
            _conv_bn(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return ops.concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _InceptionE(nn.Layer):  # 8x8 expanded
    def __init__(self, in_ch):
        super().__init__()
        self.b1 = _conv_bn(in_ch, 320, 1)
        self.b3_stem = _conv_bn(in_ch, 384, 1)
        self.b3_a = _conv_bn(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _conv_bn(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(_conv_bn(in_ch, 448, 1),
                                      _conv_bn(448, 384, 3, padding=1))
        self.b3d_a = _conv_bn(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _conv_bn(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, 1),
                                _conv_bn(in_ch, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return ops.concat(
            [self.b1(x), self.b3_a(s), self.b3_b(s), self.b3d_a(d),
             self.b3d_b(d), self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _conv_bn(3, 32, 3, stride=2), _conv_bn(32, 32, 3),
            _conv_bn(32, 64, 3, padding=1), nn.MaxPool2D(3, 2),
            _conv_bn(64, 80, 1), _conv_bn(80, 192, 3), nn.MaxPool2D(3, 2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64),
            _InceptionA(288, 64), _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768), _InceptionE(1280), _InceptionE(2048))
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.5)
        if num_classes > 0:
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        x = self.dropout(x)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)
