"""Vision Transformer (ViT-B/L/H), the BASELINE.json ladder's vision
workload.

Reference: the paddle ecosystem's ViT (PaddleClas `ppcls/arch/backbone/
model_zoo/vision_transformer.py`; the in-repo reference ships the CNN zoo
in `python/paddle/vision/models/`). TPU-first: patch embedding is one
conv (= big MXU matmul after im2col), the encoder rides the same
pre-LN transformer blocks XLA fuses well, bf16-friendly throughout.
"""

from __future__ import annotations

import dataclasses

from ... import nn
from ...nn import functional as F
from ... import ops

__all__ = ["VisionTransformer", "ViTConfig", "vit_b_16", "vit_l_16",
           "vit_h_14"]


@dataclasses.dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: float = 4.0
    dropout: float = 0.0
    attention_dropout: float = 0.0


class _EncoderBlock(nn.Layer):
    def __init__(self, cfg: ViTConfig):
        super().__init__()
        d = cfg.hidden_size
        self.ln_1 = nn.LayerNorm(d)
        self.self_attention = nn.MultiHeadAttention(
            d, cfg.num_heads, dropout=cfg.attention_dropout)
        self.dropout = nn.Dropout(cfg.dropout)
        self.ln_2 = nn.LayerNorm(d)
        hidden = int(d * cfg.mlp_ratio)
        self.mlp = nn.Sequential(
            nn.Linear(d, hidden), nn.GELU(), nn.Dropout(cfg.dropout),
            nn.Linear(hidden, d), nn.Dropout(cfg.dropout))

    def forward(self, x):
        x = x + self.dropout(self.self_attention(self.ln_1(x)))
        return x + self.mlp(self.ln_2(x))


class VisionTransformer(nn.Layer):
    def __init__(self, config: ViTConfig = None, **kwargs):
        super().__init__()
        config = config or ViTConfig(**kwargs)
        self.config = config
        d = config.hidden_size
        n_patches = (config.image_size // config.patch_size) ** 2
        self.conv_proj = nn.Conv2D(3, d, config.patch_size,
                                   stride=config.patch_size)
        self.class_token = self.create_parameter(
            [1, 1, d], default_initializer=nn.initializer.Constant(0.0))
        self.pos_embedding = self.create_parameter(
            [1, n_patches + 1, d],
            default_initializer=nn.initializer.TruncatedNormal(std=0.02))
        self.encoder = nn.LayerList(
            [_EncoderBlock(config) for _ in range(config.num_layers)])
        self.ln = nn.LayerNorm(d)
        self.heads = nn.Linear(d, config.num_classes)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x):
        b = x.shape[0]
        x = self.conv_proj(x)                      # [b, d, h', w']
        d = self.config.hidden_size
        x = ops.reshape(x, [b, d, -1])
        x = ops.transpose(x, [0, 2, 1])            # [b, n_patches, d]
        cls = ops.expand(self.class_token, [b, 1, d])
        x = ops.concat([cls, x], axis=1)
        x = self.dropout(x + self.pos_embedding)
        for blk in self.encoder:
            x = blk(x)
        x = self.ln(x)
        return self.heads(x[:, 0])

    def loss(self, images, labels):
        return F.cross_entropy(self(images), labels)


def vit_b_16(**kwargs):
    return VisionTransformer(ViTConfig(hidden_size=768, num_layers=12,
                                       num_heads=12, **kwargs))


def vit_l_16(**kwargs):
    return VisionTransformer(ViTConfig(hidden_size=1024, num_layers=24,
                                       num_heads=16, **kwargs))


def vit_h_14(**kwargs):
    return VisionTransformer(ViTConfig(hidden_size=1280, num_layers=32,
                                       num_heads=16, patch_size=14,
                                       **kwargs))
