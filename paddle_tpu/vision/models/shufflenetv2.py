"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py
API incl. the swish variant). Channel shuffle is a reshape-transpose —
free under XLA layout assignment."""

from __future__ import annotations

from ... import nn, ops

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
           "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
           "shufflenet_v2_swish"]

_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
}
_REPEATS = [4, 8, 4]


def _shuffle(x, groups=2):
    b, c, h, w = x.shape
    x = ops.reshape(x, [b, groups, c // groups, h, w])
    x = ops.transpose(x, [0, 2, 1, 3, 4])
    return ops.reshape(x, [b, c, h, w])


def _act(name):
    return nn.Swish() if name == "swish" else nn.ReLU()


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = out_ch // 2
        if stride == 1:
            main_in = in_ch // 2
        else:
            main_in = in_ch
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_ch, in_ch, 3, stride=stride, padding=1,
                          groups=in_ch, bias_attr=False),
                nn.BatchNorm2D(in_ch),
                nn.Conv2D(in_ch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act))
        self.branch2 = nn.Sequential(
            nn.Conv2D(main_in, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), _act(act),
            nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                      groups=branch, bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), _act(act))

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = ops.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = ops.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _shuffle(out)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        outs = _STAGE_OUT[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, outs[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(outs[0]), _act(act))
        self.maxpool = nn.MaxPool2D(3, 2, 1)
        stages = []
        in_ch = outs[0]
        for i, rep in enumerate(_REPEATS):
            out_ch = outs[i + 1]
            units = [_ShuffleUnit(in_ch, out_ch, 2, act)]
            units += [_ShuffleUnit(out_ch, out_ch, 1, act)
                      for _ in range(rep - 1)]
            stages.append(nn.Sequential(*units))
            in_ch = out_ch
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_ch, outs[-1], 1, bias_attr=False),
            nn.BatchNorm2D(outs[-1]), _act(act))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(outs[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv_last(self.stages(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)
