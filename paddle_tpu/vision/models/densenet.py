"""DenseNet (reference: python/paddle/vision/models/densenet.py API —
densenet121/161/169/201/264). Dense blocks concatenate feature maps;
XLA fuses the BN+ReLU+conv chains."""

from __future__ import annotations

from ... import nn, ops

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFG = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_ch)
        self.conv1 = nn.Conv2D(in_ch, bn_size * growth, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return ops.concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_ch)
        self.conv = nn.Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.relu = nn.ReLU()
        self.pool = nn.AvgPool2D(2, 2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        init_ch, growth, blocks = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv0 = nn.Conv2D(3, init_ch, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn0 = nn.BatchNorm2D(init_ch)
        self.relu = nn.ReLU()
        self.pool0 = nn.MaxPool2D(3, 2, 1)
        ch = init_ch
        stages = []
        for i, n in enumerate(blocks):
            stage = [_DenseLayer(ch + j * growth, growth, bn_size, dropout)
                     for j in range(n)]
            ch += n * growth
            stages.append(nn.Sequential(*stage))
            if i != len(blocks) - 1:
                stages.append(_Transition(ch, ch // 2))
                ch //= 2
        self.features = nn.Sequential(*stages)
        self.bn_final = nn.BatchNorm2D(ch)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.pool0(self.relu(self.bn0(self.conv0(x))))
        x = self.relu(self.bn_final(self.features(x)))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(ops.flatten(x, 1))
        return x


def _densenet(layers, pretrained=False, **kwargs):
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
