"""MobileNetV1 (reference: python/paddle/vision/models/mobilenetv1.py
API). Depthwise-separable convs — depthwise = grouped conv, XLA maps it
onto the VPU; pointwise 1x1 hits the MXU."""

from __future__ import annotations

from ... import nn, ops

__all__ = ["MobileNetV1", "mobilenet_v1"]


def _dw_sep(in_ch, out_ch, stride):
    return nn.Sequential(
        nn.Conv2D(in_ch, in_ch, 3, stride=stride, padding=1,
                  groups=in_ch, bias_attr=False),
        nn.BatchNorm2D(in_ch), nn.ReLU(),
        nn.Conv2D(in_ch, out_ch, 1, bias_attr=False),
        nn.BatchNorm2D(out_ch), nn.ReLU())


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        c = lambda ch: max(int(ch * scale), 8)  # noqa: E731
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        layers = [nn.Conv2D(3, c(32), 3, stride=2, padding=1,
                            bias_attr=False),
                  nn.BatchNorm2D(c(32)), nn.ReLU()]
        for in_ch, out_ch, s in cfg:
            layers.append(_dw_sep(c(in_ch), c(out_ch), s))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)
