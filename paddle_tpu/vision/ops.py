"""`paddle.vision.ops` — detection ops.

Parity: reference python/paddle/vision/ops.py (nms, roi_align, roi_pool,
deform_conv2d, box_coder, generate_proposals ... over phi kernels). These
back the PP-OCR / detection workloads from BASELINE.json's config ladder.
TPU-first: nms uses a fixed-iteration mask loop (compiles under jit, no
dynamic shapes); roi_align/deform_conv2d are gather+bilinear einsums.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, unwrap
from ..core.tensor import Tensor

__all__ = ["nms", "roi_align", "roi_pool", "deform_conv2d", "box_coder",
           "DeformConv2D", "box_area", "box_iou"]


def box_area(boxes):
    def fn(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return apply(fn, boxes, name="box_area")


def _iou_matrix(boxes):
    area = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def box_iou(boxes1, boxes2):
    def fn(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None, :] - inter,
                                   1e-9)
    return apply(fn, boxes1, boxes2, name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS (reference vision/ops.py nms / phi nms kernel). Returns
    kept indices sorted by score. Static-shape mask loop: O(n) iterations
    over a precomputed IoU matrix, jit-friendly."""
    b = unwrap(boxes)
    n = b.shape[0]
    s = unwrap(scores) if scores is not None else jnp.arange(
        n, 0, -1, dtype=jnp.float32)
    iou = _iou_matrix(b.astype(jnp.float32))
    if category_idxs is not None:
        # category-aware: suppress only within the same class
        cats = unwrap(category_idxs)
        same = cats[:, None] == cats[None, :]
        iou = jnp.where(same, iou, 0.0)
    order = jnp.argsort(-s)

    def body(i, keep):
        idx = order[i]
        # suppressed if any higher-scored kept box overlaps too much
        higher = keep & (jnp.arange(n) < i)
        overlaps = iou[idx, order] > iou_threshold
        suppressed = jnp.any(higher & overlaps)
        return keep.at[i].set(~suppressed)

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # keep[i] refers to order[i] (score-descending positions)
    kept = np.asarray(order)[np.asarray(keep)]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept, jnp.int64))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear grid sampling (reference roi_align phi
    kernel)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def fn(feat, rois):
        n, c, h, w = feat.shape
        num_rois = rois.shape[0]
        offset = 0.5 if aligned else 0.0
        # roi batch mapping from boxes_num
        bn = unwrap(boxes_num)
        batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                               total_repeat_length=num_rois)

        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        roi_w = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        roi_h = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_h = roi_h / ph
        bin_w = roi_w / pw
        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample points per bin
        iy = (jnp.arange(sr) + 0.5) / sr
        ix = (jnp.arange(sr) + 0.5) / sr
        py = jnp.arange(ph)
        px = jnp.arange(pw)
        # [R, ph, sr]
        ys = y1[:, None, None] + (py[None, :, None] +
                                  iy[None, None, :]) * bin_h[:, None, None]
        xs = x1[:, None, None] + (px[None, :, None] +
                                  ix[None, None, :]) * bin_w[:, None, None]

        def bilinear(b_idx, yy, xx):
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1_ = jnp.clip(y0 + 1, 0, h - 1)
            x1_ = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy - y0, 0, 1)
            wx = jnp.clip(xx - x0, 0, 1)
            fm = feat[b_idx]  # [c, h, w]
            v00 = fm[:, y0, x0]
            v01 = fm[:, y0, x1_]
            v10 = fm[:, y1_, x0]
            v11 = fm[:, y1_, x1_]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                    v10 * wy * (1 - wx) + v11 * wy * wx)

        def one_roi(r):
            b_idx = batch_idx[r]
            # [ph, sr] x [pw, sr] grids
            vals = jax.vmap(lambda yy: jax.vmap(
                lambda xx: bilinear(b_idx, yy, xx))(
                    xs[r].reshape(-1)))(ys[r].reshape(-1))
            # vals: [ph*sr, pw*sr, c]
            vals = vals.reshape(ph, sr, pw, sr, c)
            return vals.mean(axis=(1, 3)).transpose(2, 0, 1)  # [c,ph,pw]

        return jax.vmap(one_roi)(jnp.arange(num_rois))

    return apply(fn, x, boxes, name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    return roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                     sampling_ratio=1, aligned=False)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference deform_conv2d phi kernel; the
    PP-OCR backbone op). Implemented as offset-shifted bilinear sampling +
    matmul — gathers vectorize on TPU."""
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else \
        tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) else \
        tuple(dilation)

    def fn(xa, off, w, *rest):
        b, c, h, wd = xa.shape
        oc, cg, kh, kw = w.shape
        mask_a = None
        bias_a = None
        rest = list(rest)
        if mask is not None:
            mask_a = rest.pop(0)
        if bias is not None:
            bias_a = rest.pop(0)
        out_h = (h + 2 * padding[0] - dilation[0] * (kh - 1) - 1) \
            // stride[0] + 1
        out_w = (wd + 2 * padding[1] - dilation[1] * (kw - 1) - 1) \
            // stride[1] + 1
        xp = jnp.pad(xa, ((0, 0), (0, 0), (padding[0], padding[0]),
                          (padding[1], padding[1])))
        ph, pw = xp.shape[2], xp.shape[3]
        # base sampling grid [out_h, out_w, kh, kw]
        gy = (jnp.arange(out_h) * stride[0])[:, None, None, None] + \
            (jnp.arange(kh) * dilation[0])[None, None, :, None]
        gx = (jnp.arange(out_w) * stride[1])[None, :, None, None] + \
            (jnp.arange(kw) * dilation[1])[None, None, None, :]
        gy = jnp.broadcast_to(gy, (out_h, out_w, kh, kw)).astype(
            jnp.float32)
        gx = jnp.broadcast_to(gx, (out_h, out_w, kh, kw)).astype(
            jnp.float32)
        # offsets: [b, 2*dg*kh*kw, out_h, out_w] (y,x interleaved pairs)
        off = off.reshape(b, deformable_groups, kh * kw, 2, out_h, out_w)
        oy = off[:, :, :, 0].transpose(0, 1, 3, 4, 2).reshape(
            b, deformable_groups, out_h, out_w, kh, kw)
        ox = off[:, :, :, 1].transpose(0, 1, 3, 4, 2).reshape(
            b, deformable_groups, out_h, out_w, kh, kw)
        sy = gy[None, None] + oy
        sx = gx[None, None] + ox

        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy = sy - y0
        wx = sx - x0

        def gather(feat_dg, yy, xx):
            yi = jnp.clip(yy.astype(jnp.int32), 0, ph - 1)
            xi = jnp.clip(xx.astype(jnp.int32), 0, pw - 1)
            valid = (yy >= 0) & (yy <= ph - 1) & (xx >= 0) & (xx <= pw - 1)
            v = feat_dg[:, yi, xi]
            return jnp.where(valid[None], v, 0.0)

        cd = c // deformable_groups
        samples = []
        for dg in range(deformable_groups):
            feat = xp[:, dg * cd:(dg + 1) * cd]  # [b, cd, ph, pw]

            def per_b(fb, y0b, x0b, wyb, wxb):
                v00 = gather(fb, y0b, x0b)
                v01 = gather(fb, y0b, x0b + 1)
                v10 = gather(fb, y0b + 1, x0b)
                v11 = gather(fb, y0b + 1, x0b + 1)
                return (v00 * (1 - wyb) * (1 - wxb) +
                        v01 * (1 - wyb) * wxb +
                        v10 * wyb * (1 - wxb) + v11 * wyb * wxb)

            s = jax.vmap(per_b)(feat, y0[:, dg], x0[:, dg], wy[:, dg],
                                wx[:, dg])
            samples.append(s)  # [b, cd, out_h, out_w, kh, kw]
        sampled = jnp.concatenate(samples, axis=1)
        if mask_a is not None:
            m = mask_a.reshape(b, deformable_groups, kh * kw, out_h,
                               out_w).transpose(0, 1, 3, 4, 2).reshape(
                b, deformable_groups, out_h, out_w, kh, kw)
            m = jnp.repeat(m, cd, axis=1)
            sampled = sampled * m
        # conv as einsum over sampled patches
        out = jnp.einsum("bchwkl,ockl->bohw",
                         sampled.astype(jnp.float32),
                         w.astype(jnp.float32))
        if bias_a is not None:
            out = out + bias_a[None, :, None, None]
        return out.astype(xa.dtype)

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply(fn, *args, name="deform_conv2d")


class DeformConv2D:
    """Layer form of deform_conv2d (reference vision/ops.py
    DeformConv2D)."""

    def __new__(cls, in_channels, out_channels, kernel_size, stride=1,
                padding=0, dilation=1, deformable_groups=1, groups=1,
                weight_attr=None, bias_attr=None):
        from .. import nn

        class _Layer(nn.Layer):
            def __init__(self):
                super().__init__()
                k = kernel_size if isinstance(kernel_size, (list, tuple)) \
                    else (kernel_size, kernel_size)
                self.weight = self.create_parameter(
                    [out_channels, in_channels // groups, *k],
                    attr=weight_attr,
                    default_initializer=nn.initializer.XavierNormal())
                if bias_attr is not False:
                    self.bias = self.create_parameter([out_channels],
                                                      attr=bias_attr,
                                                      is_bias=True)
                else:
                    self.bias = None

            def forward(self, x, offset, mask=None):
                return deform_conv2d(
                    x, offset, self.weight, self.bias, stride, padding,
                    dilation, deformable_groups, groups, mask)

        return _Layer()


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """reference box_coder op (SSD-style box encode/decode)."""

    def fn(pb, pbv, tb):
        pw = pb[:, 2] - pb[:, 0] + (0 if box_normalized else 1)
        ph = pb[:, 3] - pb[:, 1] + (0 if box_normalized else 1)
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + (0 if box_normalized else 1)
            th = tb[:, 3] - tb[:, 1] + (0 if box_normalized else 1)
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            ox = (tcx - pcx) / pw / pbv[:, 0]
            oy = (tcy - pcy) / ph / pbv[:, 1]
            ow = jnp.log(tw / pw) / pbv[:, 2]
            oh = jnp.log(th / ph) / pbv[:, 3]
            return jnp.stack([ox, oy, ow, oh], axis=1)
        # decode
        ox = tb[..., 0] * pbv[:, 0] * pw + pcx
        oy = tb[..., 1] * pbv[:, 1] * ph + pcy
        ow = jnp.exp(tb[..., 2] * pbv[:, 2]) * pw
        oh = jnp.exp(tb[..., 3] * pbv[:, 3]) * ph
        return jnp.stack([ox - ow * 0.5, oy - oh * 0.5,
                          ox + ow * 0.5, oy + oh * 0.5], axis=-1)

    return apply(fn, prior_box, prior_box_var, target_box,
                 name="box_coder")
