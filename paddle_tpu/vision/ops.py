"""`paddle.vision.ops` — detection ops.

Parity: reference python/paddle/vision/ops.py (nms, roi_align, roi_pool,
deform_conv2d, box_coder, generate_proposals ... over phi kernels). These
back the PP-OCR / detection workloads from BASELINE.json's config ladder.
TPU-first: nms uses a fixed-iteration mask loop (compiles under jit, no
dynamic shapes); roi_align/deform_conv2d are gather+bilinear einsums.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, unwrap
from ..core.tensor import Tensor

__all__ = ["nms", "roi_align", "roi_pool", "deform_conv2d", "box_coder",
           "DeformConv2D", "box_area", "box_iou", "RoIAlign", "RoIPool",
           "PSRoIPool", "psroi_pool", "read_file", "decode_jpeg",
           "prior_box", "yolo_box", "yolo_loss", "matrix_nms",
           "distribute_fpn_proposals", "generate_proposals"]


def box_area(boxes):
    def fn(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return apply(fn, boxes, name="box_area")


def _iou_matrix(boxes):
    area = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def box_iou(boxes1, boxes2):
    def fn(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None, :] - inter,
                                   1e-9)
    return apply(fn, boxes1, boxes2, name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS (reference vision/ops.py nms / phi nms kernel). Returns
    kept indices sorted by score. Static-shape mask loop: O(n) iterations
    over a precomputed IoU matrix, jit-friendly."""
    b = unwrap(boxes)
    n = b.shape[0]
    s = unwrap(scores) if scores is not None else jnp.arange(
        n, 0, -1, dtype=jnp.float32)
    iou = _iou_matrix(b.astype(jnp.float32))
    if category_idxs is not None:
        # category-aware: suppress only within the same class
        cats = unwrap(category_idxs)
        same = cats[:, None] == cats[None, :]
        iou = jnp.where(same, iou, 0.0)
    order = jnp.argsort(-s)

    def body(i, keep):
        idx = order[i]
        # suppressed if any higher-scored kept box overlaps too much
        higher = keep & (jnp.arange(n) < i)
        overlaps = iou[idx, order] > iou_threshold
        suppressed = jnp.any(higher & overlaps)
        return keep.at[i].set(~suppressed)

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # keep[i] refers to order[i] (score-descending positions)
    kept = np.asarray(order)[np.asarray(keep)]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept, jnp.int64))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear grid sampling (reference roi_align phi
    kernel)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def fn(feat, rois):
        n, c, h, w = feat.shape
        num_rois = rois.shape[0]
        offset = 0.5 if aligned else 0.0
        # roi batch mapping from boxes_num
        bn = unwrap(boxes_num)
        batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                               total_repeat_length=num_rois)

        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        roi_w = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        roi_h = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_h = roi_h / ph
        bin_w = roi_w / pw
        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample points per bin
        iy = (jnp.arange(sr) + 0.5) / sr
        ix = (jnp.arange(sr) + 0.5) / sr
        py = jnp.arange(ph)
        px = jnp.arange(pw)
        # [R, ph, sr]
        ys = y1[:, None, None] + (py[None, :, None] +
                                  iy[None, None, :]) * bin_h[:, None, None]
        xs = x1[:, None, None] + (px[None, :, None] +
                                  ix[None, None, :]) * bin_w[:, None, None]

        def bilinear(b_idx, yy, xx):
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1_ = jnp.clip(y0 + 1, 0, h - 1)
            x1_ = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy - y0, 0, 1)
            wx = jnp.clip(xx - x0, 0, 1)
            fm = feat[b_idx]  # [c, h, w]
            v00 = fm[:, y0, x0]
            v01 = fm[:, y0, x1_]
            v10 = fm[:, y1_, x0]
            v11 = fm[:, y1_, x1_]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                    v10 * wy * (1 - wx) + v11 * wy * wx)

        def one_roi(r):
            b_idx = batch_idx[r]
            # [ph, sr] x [pw, sr] grids
            vals = jax.vmap(lambda yy: jax.vmap(
                lambda xx: bilinear(b_idx, yy, xx))(
                    xs[r].reshape(-1)))(ys[r].reshape(-1))
            # vals: [ph*sr, pw*sr, c]
            vals = vals.reshape(ph, sr, pw, sr, c)
            return vals.mean(axis=(1, 3)).transpose(2, 0, 1)  # [c,ph,pw]

        return jax.vmap(one_roi)(jnp.arange(num_rois))

    return apply(fn, x, boxes, name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    return roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                     sampling_ratio=1, aligned=False)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference deform_conv2d phi kernel; the
    PP-OCR backbone op). Implemented as offset-shifted bilinear sampling +
    matmul — gathers vectorize on TPU."""
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else \
        tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) else \
        tuple(dilation)

    def fn(xa, off, w, *rest):
        b, c, h, wd = xa.shape
        oc, cg, kh, kw = w.shape
        mask_a = None
        bias_a = None
        rest = list(rest)
        if mask is not None:
            mask_a = rest.pop(0)
        if bias is not None:
            bias_a = rest.pop(0)
        out_h = (h + 2 * padding[0] - dilation[0] * (kh - 1) - 1) \
            // stride[0] + 1
        out_w = (wd + 2 * padding[1] - dilation[1] * (kw - 1) - 1) \
            // stride[1] + 1
        xp = jnp.pad(xa, ((0, 0), (0, 0), (padding[0], padding[0]),
                          (padding[1], padding[1])))
        ph, pw = xp.shape[2], xp.shape[3]
        # base sampling grid [out_h, out_w, kh, kw]
        gy = (jnp.arange(out_h) * stride[0])[:, None, None, None] + \
            (jnp.arange(kh) * dilation[0])[None, None, :, None]
        gx = (jnp.arange(out_w) * stride[1])[None, :, None, None] + \
            (jnp.arange(kw) * dilation[1])[None, None, None, :]
        gy = jnp.broadcast_to(gy, (out_h, out_w, kh, kw)).astype(
            jnp.float32)
        gx = jnp.broadcast_to(gx, (out_h, out_w, kh, kw)).astype(
            jnp.float32)
        # offsets: [b, 2*dg*kh*kw, out_h, out_w] (y,x interleaved pairs)
        off = off.reshape(b, deformable_groups, kh * kw, 2, out_h, out_w)
        oy = off[:, :, :, 0].transpose(0, 1, 3, 4, 2).reshape(
            b, deformable_groups, out_h, out_w, kh, kw)
        ox = off[:, :, :, 1].transpose(0, 1, 3, 4, 2).reshape(
            b, deformable_groups, out_h, out_w, kh, kw)
        sy = gy[None, None] + oy
        sx = gx[None, None] + ox

        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy = sy - y0
        wx = sx - x0

        def gather(feat_dg, yy, xx):
            yi = jnp.clip(yy.astype(jnp.int32), 0, ph - 1)
            xi = jnp.clip(xx.astype(jnp.int32), 0, pw - 1)
            valid = (yy >= 0) & (yy <= ph - 1) & (xx >= 0) & (xx <= pw - 1)
            v = feat_dg[:, yi, xi]
            return jnp.where(valid[None], v, 0.0)

        cd = c // deformable_groups
        samples = []
        for dg in range(deformable_groups):
            feat = xp[:, dg * cd:(dg + 1) * cd]  # [b, cd, ph, pw]

            def per_b(fb, y0b, x0b, wyb, wxb):
                v00 = gather(fb, y0b, x0b)
                v01 = gather(fb, y0b, x0b + 1)
                v10 = gather(fb, y0b + 1, x0b)
                v11 = gather(fb, y0b + 1, x0b + 1)
                return (v00 * (1 - wyb) * (1 - wxb) +
                        v01 * (1 - wyb) * wxb +
                        v10 * wyb * (1 - wxb) + v11 * wyb * wxb)

            s = jax.vmap(per_b)(feat, y0[:, dg], x0[:, dg], wy[:, dg],
                                wx[:, dg])
            samples.append(s)  # [b, cd, out_h, out_w, kh, kw]
        sampled = jnp.concatenate(samples, axis=1)
        if mask_a is not None:
            m = mask_a.reshape(b, deformable_groups, kh * kw, out_h,
                               out_w).transpose(0, 1, 3, 4, 2).reshape(
                b, deformable_groups, out_h, out_w, kh, kw)
            m = jnp.repeat(m, cd, axis=1)
            sampled = sampled * m
        # conv as einsum over sampled patches
        out = jnp.einsum("bchwkl,ockl->bohw",
                         sampled.astype(jnp.float32),
                         w.astype(jnp.float32))
        if bias_a is not None:
            out = out + bias_a[None, :, None, None]
        return out.astype(xa.dtype)

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply(fn, *args, name="deform_conv2d")


class DeformConv2D:
    """Layer form of deform_conv2d (reference vision/ops.py
    DeformConv2D)."""

    def __new__(cls, in_channels, out_channels, kernel_size, stride=1,
                padding=0, dilation=1, deformable_groups=1, groups=1,
                weight_attr=None, bias_attr=None):
        from .. import nn

        class _Layer(nn.Layer):
            def __init__(self):
                super().__init__()
                k = kernel_size if isinstance(kernel_size, (list, tuple)) \
                    else (kernel_size, kernel_size)
                self.weight = self.create_parameter(
                    [out_channels, in_channels // groups, *k],
                    attr=weight_attr,
                    default_initializer=nn.initializer.XavierNormal())
                if bias_attr is not False:
                    self.bias = self.create_parameter([out_channels],
                                                      attr=bias_attr,
                                                      is_bias=True)
                else:
                    self.bias = None

            def forward(self, x, offset, mask=None):
                return deform_conv2d(
                    x, offset, self.weight, self.bias, stride, padding,
                    dilation, deformable_groups, groups, mask)

        return _Layer()


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """reference box_coder op (SSD-style box encode/decode)."""

    def fn(pb, pbv, tb):
        pw = pb[:, 2] - pb[:, 0] + (0 if box_normalized else 1)
        ph = pb[:, 3] - pb[:, 1] + (0 if box_normalized else 1)
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + (0 if box_normalized else 1)
            th = tb[:, 3] - tb[:, 1] + (0 if box_normalized else 1)
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            ox = (tcx - pcx) / pw / pbv[:, 0]
            oy = (tcy - pcy) / ph / pbv[:, 1]
            ow = jnp.log(tw / pw) / pbv[:, 2]
            oh = jnp.log(th / ph) / pbv[:, 3]
            return jnp.stack([ox, oy, ow, oh], axis=1)
        # decode
        ox = tb[..., 0] * pbv[:, 0] * pw + pcx
        oy = tb[..., 1] * pbv[:, 1] * ph + pcy
        ow = jnp.exp(tb[..., 2] * pbv[:, 2]) * pw
        oh = jnp.exp(tb[..., 3] * pbv[:, 3]) * ph
        return jnp.stack([ox - ow * 0.5, oy - oh * 0.5,
                          ox + ow * 0.5, oy + oh * 0.5], axis=-1)

    return apply(fn, prior_box, prior_box_var, target_box,
                 name="box_coder")


class RoIAlign:
    """Layer form of roi_align (reference vision/ops.py RoIAlign)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale, aligned=aligned)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference psroi_pool): input
    channels C = out_c * oh * ow; each output bin pools its own channel
    group."""
    import jax.numpy as jnp

    from ..core.dispatch import apply, as_index, unwrap

    oh = ow = output_size if isinstance(output_size, int) else None
    if oh is None:
        oh, ow = output_size
    bxs = unwrap(boxes)

    def fn(a):
        n, c, h, w = a.shape
        out_c = c // (oh * ow)
        pooled = roi_align(
            Tensor(a.reshape(n, c, h, w)), Tensor(bxs),
            boxes_num, (oh, ow), spatial_scale, sampling_ratio=1,
            aligned=False)
        p = unwrap(pooled)  # [nb, c, oh, ow]
        nb = p.shape[0]
        p = p.reshape(nb, out_c, oh, ow, oh, ow)
        # select the (i, j)-th channel plane for output bin (i, j)
        ii = jnp.arange(oh)
        jj = jnp.arange(ow)
        return p[:, :, ii[:, None], jj[None, :], ii[:, None], jj[None, :]]
    return apply(fn, x, name="psroi_pool")


class PSRoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def read_file(filename, name=None):
    """Raw file bytes as a uint8 tensor (reference read_file)."""
    import numpy as np

    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(data)


def decode_jpeg(x, mode="unchanged", name=None):
    """JPEG decode (reference decode_jpeg, nvjpeg-backed). Host-side
    decode through Pillow/torchvision when available."""
    import io

    import numpy as np

    from ..core.dispatch import unwrap

    raw = bytes(np.asarray(unwrap(x), np.uint8))
    try:
        from PIL import Image

        img = np.asarray(Image.open(io.BytesIO(raw)))
    except ImportError:
        try:
            import torchvision.io as tvio
            import torch

            img = tvio.decode_jpeg(
                torch.frombuffer(bytearray(raw), dtype=torch.uint8)
            ).numpy().transpose(1, 2, 0)
        except Exception as e:  # pragma: no cover
            raise RuntimeError(
                "decode_jpeg needs Pillow or torchvision") from e
    if img.ndim == 2:
        img = img[None]
    else:
        img = img.transpose(2, 0, 1)
    return Tensor(img.copy())


def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior (anchor) boxes (reference prior_box op)."""
    import numpy as np

    from ..core.dispatch import unwrap

    fh, fw = unwrap(input).shape[2:]
    ih, iw = unwrap(image).shape[2:]
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if ar != 1.0:
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    boxes = []
    vars_ = []
    for y in range(fh):
        for x in range(fw):
            cx = (x + offset) * step_w
            cy = (y + offset) * step_h
            cell = []
            for si, ms in enumerate(min_sizes):
                for ar in ars:
                    bw = ms * np.sqrt(ar) / 2
                    bh = ms / np.sqrt(ar) / 2
                    cell.append([(cx - bw) / iw, (cy - bh) / ih,
                                 (cx + bw) / iw, (cy + bh) / ih])
                if max_sizes:
                    ms2 = np.sqrt(ms * max_sizes[si])
                    cell.append([(cx - ms2 / 2) / iw, (cy - ms2 / 2) / ih,
                                 (cx + ms2 / 2) / iw, (cy + ms2 / 2) / ih])
            boxes.append(cell)
            vars_.append([list(variance)] * len(cell))
    out = np.asarray(boxes, "float32").reshape(fh, fw, -1, 4)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.asarray(vars_, "float32").reshape(fh, fw, -1, 4)
    return Tensor(out), Tensor(var)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """YOLOv3 head decode (reference yolo_box op): raw feature map ->
    (boxes [N, hwa, 4], scores [N, hwa, class_num])."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply, unwrap

    anchors = np.asarray(anchors, "float32").reshape(-1, 2)
    na = anchors.shape[0]
    imgs = unwrap(img_size)

    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, na, 5 + class_num, h, w)
        gx = jnp.arange(w, dtype=jnp.float32)
        gy = jnp.arange(h, dtype=jnp.float32)
        bx = (jax.nn.sigmoid(a[:, :, 0]) * scale_x_y
              - (scale_x_y - 1) / 2 + gx[None, None, None, :]) / w
        by = (jax.nn.sigmoid(a[:, :, 1]) * scale_x_y
              - (scale_x_y - 1) / 2 + gy[None, None, :, None]) / h
        in_w = w * downsample_ratio
        in_h = h * downsample_ratio
        bw = jnp.exp(a[:, :, 2]) * anchors[None, :, 0, None, None] / in_w
        bh = jnp.exp(a[:, :, 3]) * anchors[None, :, 1, None, None] / in_h
        conf = jax.nn.sigmoid(a[:, :, 4])
        probs = jax.nn.sigmoid(a[:, :, 5:]) * conf[:, :, None]
        probs = jnp.where(conf[:, :, None] < conf_thresh, 0.0, probs)
        ih = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        iw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x0 = (bx - bw / 2) * iw
        y0 = (by - bh / 2) * ih
        x1 = (bx + bw / 2) * iw
        y1 = (by + bh / 2) * ih
        if clip_bbox:
            x0 = jnp.clip(x0, 0, iw - 1)
            y0 = jnp.clip(y0, 0, ih - 1)
            x1 = jnp.clip(x1, 0, iw - 1)
            y1 = jnp.clip(y1, 0, ih - 1)
        boxes = jnp.stack([x0, y0, x1, y1], -1).reshape(n, -1, 4)
        scores = probs.transpose(0, 1, 3, 4, 2).reshape(
            n, -1, class_num)
        return boxes, scores
    return apply(fn, x, name="yolo_box")


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """YOLOv3 training loss (reference yolo3_loss op): coordinate +
    objectness + class terms over assigned anchors."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply, as_index, unwrap

    anchors_np = np.asarray(anchors, "float32").reshape(-1, 2)
    mask = list(anchor_mask)
    na = len(mask)
    gtb = unwrap(gt_box).astype(jnp.float32)   # [n, b, 4] cx cy w h (0-1)
    gtl = as_index(unwrap(gt_label))           # [n, b]

    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, na, 5 + class_num, h, w)
        in_w = w * downsample_ratio
        in_h = h * downsample_ratio

        tx = jax.nn.sigmoid(a[:, :, 0])
        ty = jax.nn.sigmoid(a[:, :, 1])
        tw = a[:, :, 2]
        th = a[:, :, 3]
        tobj = a[:, :, 4]
        tcls = a[:, :, 5:]

        # build targets per gt: which cell + which anchor (best iou by wh)
        gx = gtb[..., 0] * w
        gy = gtb[..., 1] * h
        gi = jnp.clip(gx.astype(jnp.int32), 0, w - 1)
        gj = jnp.clip(gy.astype(jnp.int32), 0, h - 1)
        gw = gtb[..., 2] * in_w
        gh_ = gtb[..., 3] * in_h
        wh = jnp.stack([gw, gh_], -1)[:, :, None, :]     # [n,b,1,2]
        aw = jnp.asarray(anchors_np)[None, None, mask]   # [1,1,na,2]
        inter = jnp.minimum(wh, aw).prod(-1)
        union = wh.prod(-1) + aw.prod(-1) - inter
        iou = inter / jnp.maximum(union, 1e-9)
        best = jnp.argmax(iou, -1)                        # [n, b]
        valid = (gtb[..., 2] > 0) & (gtb[..., 3] > 0)

        batch = jnp.arange(n)[:, None]
        tgt_x = gx - gi
        tgt_y = gy - gj
        aw_sel = jnp.asarray(anchors_np)[jnp.asarray(mask)[best]]
        tgt_w = jnp.log(jnp.maximum(gw / aw_sel[..., 0], 1e-9))
        tgt_h = jnp.log(jnp.maximum(gh_ / aw_sel[..., 1], 1e-9))
        scale = 2.0 - gtb[..., 2] * gtb[..., 3]

        def pick(t):
            return t[batch, best, gj, gi]
        l_x = jnp.where(valid, scale * (pick(tx) - tgt_x) ** 2, 0.0)
        l_y = jnp.where(valid, scale * (pick(ty) - tgt_y) ** 2, 0.0)
        l_w = jnp.where(valid, scale * (pick(tw) - tgt_w) ** 2, 0.0)
        l_h = jnp.where(valid, scale * (pick(th) - tgt_h) ** 2, 0.0)

        obj_target = jnp.zeros((n, na, h, w)).at[
            batch, best, gj, gi].max(valid.astype(jnp.float32))
        bce = jnp.maximum(tobj, 0) - tobj * obj_target + \
            jnp.log1p(jnp.exp(-jnp.abs(tobj)))
        l_obj = jnp.sum(bce, axis=(1, 2, 3))

        smooth = 1.0 / class_num if use_label_smooth else 0.0
        cls_target = jax.nn.one_hot(gtl, class_num) * (1 - 2 * smooth) \
            + smooth
        cls_logit = tcls[batch, best, :, gj, gi]
        cbce = jnp.maximum(cls_logit, 0) - cls_logit * cls_target + \
            jnp.log1p(jnp.exp(-jnp.abs(cls_logit)))
        l_cls = jnp.where(valid[..., None], cbce, 0.0).sum((-1, -2))

        per = (l_x + l_y + l_w + l_h).sum(-1) + l_obj + l_cls
        return per
    return apply(fn, x, name="yolo_loss")


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2; reference matrix_nms op): soft decay by
    pairwise IoU instead of hard suppression."""
    import numpy as np

    from ..core.dispatch import unwrap

    bx = np.asarray(unwrap(bboxes))
    sc = np.asarray(unwrap(scores))
    outs = []
    idxs = []
    nums = []
    for n in range(bx.shape[0]):
        cls_best = sc[n].max(0)
        cls_id = sc[n].argmax(0)
        keep = np.where(cls_best > score_threshold)[0]
        if keep.size == 0:
            nums.append(0)
            continue
        order = keep[np.argsort(-cls_best[keep])][:nms_top_k]
        b = bx[n][order]
        s = cls_best[order]
        x0 = np.maximum(b[:, None, 0], b[None, :, 0])
        y0 = np.maximum(b[:, None, 1], b[None, :, 1])
        x1 = np.minimum(b[:, None, 2], b[None, :, 2])
        y1 = np.minimum(b[:, None, 3], b[None, :, 3])
        inter = np.clip(x1 - x0, 0, None) * np.clip(y1 - y0, 0, None)
        area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        iou = inter / np.maximum(area[:, None] + area[None, :] - inter,
                                 1e-9)
        iou = np.triu(iou, 1)
        # max_iou[i]: the suppressor i's own worst overlap with anything
        # ranked above it — the compensation term is indexed by the
        # SUPPRESSOR (rows), not the suppressed box (columns)
        max_iou = iou.max(0)
        if use_gaussian:
            decay = np.exp(-(iou ** 2 - max_iou[:, None] ** 2)
                           / gaussian_sigma).min(0)
        else:
            decay = ((1 - iou) / np.maximum(1 - max_iou[:, None], 1e-9)
                     ).min(0)
        s2 = s * decay
        keep2 = np.where(s2 > post_threshold)[0][:keep_top_k]
        rows = np.stack([cls_id[order][keep2].astype("float32"),
                         s2[keep2]], 1)
        outs.append(np.concatenate([rows, b[keep2]], 1))
        idxs.append(order[keep2] + n * bx.shape[1])
        nums.append(len(keep2))
    out = np.concatenate(outs, 0) if outs else np.zeros((0, 6), "float32")
    result = [Tensor(out)]
    if return_index:
        result.append(Tensor(np.concatenate(idxs).astype("int64")
                             if idxs else np.zeros((0,), "int64")))
    if return_rois_num:
        result.append(Tensor(np.asarray(nums, "int64")))
    return tuple(result) if len(result) > 1 else result[0]


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference
    distribute_fpn_proposals)."""
    import numpy as np

    from ..core.dispatch import unwrap

    rois = np.asarray(unwrap(fpn_rois))
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = np.sqrt(np.maximum(w * h, 1e-9))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(int)
    multi = []
    restore = []
    nums = []
    for l in range(min_level, max_level + 1):
        sel = np.where(lvl == l)[0]
        multi.append(Tensor(rois[sel]))
        restore.append(sel)
        nums.append(Tensor(np.asarray([len(sel)], "int32")))
    order = np.concatenate(restore) if restore else np.zeros(0, int)
    inv = np.empty_like(order)
    inv[order] = np.arange(order.size)
    return multi, Tensor(inv.astype("int32")[:, None]), nums


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference generate_proposals): decode
    deltas at anchors, clip, filter small, NMS."""
    import numpy as np

    from ..core.dispatch import unwrap

    sc = np.asarray(unwrap(scores))        # [n, a, h, w]
    bd = np.asarray(unwrap(bbox_deltas))   # [n, 4a, h, w]
    ims = np.asarray(unwrap(img_size))     # [n, 2] (h, w)
    anc = np.asarray(unwrap(anchors)).reshape(-1, 4)
    var = np.asarray(unwrap(variances)).reshape(-1, 4)

    all_rois = []
    nums = []
    for n in range(sc.shape[0]):
        s = sc[n].transpose(1, 2, 0).reshape(-1)
        d = bd[n].reshape(-1, 4, sc.shape[2], sc.shape[3]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s = s[order]
        d = d[order]
        a = anc[order % anc.shape[0]] if anc.shape[0] != d.shape[0] \
            else anc[order]
        v = var[order % var.shape[0]] if var.shape[0] != d.shape[0] \
            else var[order]
        aw = a[:, 2] - a[:, 0]
        ah = a[:, 3] - a[:, 1]
        acx = a[:, 0] + aw / 2
        acy = a[:, 1] + ah / 2
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        bw = np.exp(np.minimum(v[:, 2] * d[:, 2], 10)) * aw
        bh = np.exp(np.minimum(v[:, 3] * d[:, 3], 10)) * ah
        boxes = np.stack([cx - bw / 2, cy - bh / 2, cx + bw / 2,
                          cy + bh / 2], 1)
        ih, iw = ims[n]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - 1)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - 1)
        keep = np.where((boxes[:, 2] - boxes[:, 0] >= min_size) &
                        (boxes[:, 3] - boxes[:, 1] >= min_size))[0]
        boxes = boxes[keep]
        s = s[keep]
        # greedy nms
        order2 = np.argsort(-s)
        picked = []
        while order2.size and len(picked) < post_nms_top_n:
            i = order2[0]
            picked.append(i)
            if order2.size == 1:
                break
            rest = order2[1:]
            xx0 = np.maximum(boxes[i, 0], boxes[rest, 0])
            yy0 = np.maximum(boxes[i, 1], boxes[rest, 1])
            xx1 = np.minimum(boxes[i, 2], boxes[rest, 2])
            yy1 = np.minimum(boxes[i, 3], boxes[rest, 3])
            inter = np.clip(xx1 - xx0, 0, None) * np.clip(yy1 - yy0, 0,
                                                          None)
            ai = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
            ar = (boxes[rest, 2] - boxes[rest, 0]) * \
                (boxes[rest, 3] - boxes[rest, 1])
            iou = inter / np.maximum(ai + ar - inter, 1e-9)
            order2 = rest[iou <= nms_thresh]
        all_rois.append(boxes[picked])
        nums.append(len(picked))
    rois = np.concatenate(all_rois, 0) if all_rois else \
        np.zeros((0, 4), "float32")
    out = (Tensor(rois.astype("float32")),
           Tensor(np.concatenate([np.full(k, i) for i, k in
                                  enumerate(nums)]).astype("float32")
                  if nums else np.zeros(0, "float32")))
    if return_rois_num:
        return out + (Tensor(np.asarray(nums, "int32")),)
    return out
