"""Numerics debugging.

Parity: reference `python/paddle/amp/debugging.py` — `TensorCheckerConfig`
(:174), `enable_tensor_checker/disable_tensor_checker`, `check_numerics`
(:362), op-stats collection (:482) — backed by `FLAGS_check_nan_inf` and
the per-op check hook in core.dispatch (the analogue of the generated
ad_funcs' CheckTensorHasNanOrInf, paddle/fluid/eager/nan_inf_utils.h:38).
"""

from __future__ import annotations

import collections

import jax
import jax.numpy as jnp

from ..core import flags as flags_mod
from ..core.tensor import Tensor, _host_read
from ..profiler import metrics as _metrics

_C_CHECKED = _metrics.counter("amp.check_naninf.checked")
_C_FLAGGED = _metrics.counter("amp.check_naninf.flagged")
_C_OP_CALLS = {k: _metrics.counter(f"amp.op_calls.{k}")
               for k in ("fp32", "fp16", "bf16", "other")}

__all__ = ["TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker", "check_numerics",
           "enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "DebugMode"]


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=DebugMode.
                 CHECK_NAN_INF_AND_ABORT, output_dir=None,
                 checked_op_list=None, skipped_op_list=None,
                 debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.checked_op_list = set(checked_op_list or ())
        self.skipped_op_list = set(skipped_op_list or ())

    def _apply(self):
        flags_mod.set_flags({
            "FLAGS_check_nan_inf": self.enable,
            "FLAGS_check_nan_inf_level": self.debug_mode})


_config = None


def enable_tensor_checker(checker_config=None):
    global _config
    _config = checker_config or TensorCheckerConfig()
    _config._apply()


def disable_tensor_checker():
    flags_mod.set_flags({"FLAGS_check_nan_inf": False})


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """reference debugging.py:362: returns (num_nan, num_inf, num_zero)."""
    arr = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    nan = jnp.sum(jnp.isnan(arr)).astype(jnp.int64)
    inf = jnp.sum(jnp.isinf(arr)).astype(jnp.int64)
    zero = jnp.sum(arr == 0).astype(jnp.int64)
    return Tensor(nan), Tensor(inf), Tensor(zero)


def check_array(name, arr):
    """Dispatch hook: abort/warn on non-finite op outputs (eager only)."""
    if isinstance(arr, jax.core.Tracer):
        return
    if not jnp.issubdtype(arr.dtype, jnp.floating):
        return
    _C_CHECKED.inc()
    # the bool() forces a device sync — worth a Sync span of its own:
    # with FLAGS_check_nan_inf on, this is usually the dominant cost
    finite = _host_read(f"check_naninf/{name}",
                        lambda: bool(jnp.isfinite(arr).all()))
    if finite:
        return
    _C_FLAGGED.inc()
    level = flags_mod.flag("FLAGS_check_nan_inf_level")
    msg = f"Operator {name!r} produced NaN/Inf output"
    if level == DebugMode.CHECK_NAN_INF_AND_ABORT:
        raise FloatingPointError(msg)
    import warnings
    warnings.warn(msg)


# -- op stats (reference debugging.py:482) --------------------------------
_op_stats = None


def enable_operator_stats_collection():
    global _op_stats
    _op_stats = collections.defaultdict(
        lambda: {"fp32": 0, "fp16": 0, "bf16": 0, "other": 0})
    # dispatch keeps an epoch-gated snapshot of whether op-stats are
    # live (it used to probe sys.modules per op); make the toggle
    # visible to warm call sites on the very next op
    flags_mod._bump_epoch()


def disable_operator_stats_collection():
    global _op_stats
    stats = _op_stats
    _op_stats = None
    flags_mod._bump_epoch()
    if stats:
        print("<{:-^120}>".format(" op list "))
        fmt = "{:<50} | {:<10} | {:<10} | {:<10} | {:<10}"
        print(fmt.format("OP Type", "FP16 Calls", "BF16 Calls",
                         "FP32 Calls", "Other Calls"))
        for op, c in sorted(stats.items()):
            print(fmt.format(op, c["fp16"], c["bf16"], c["fp32"],
                             c["other"]))
        print("<{:-^120}>".format(""))
    return stats


class collect_operator_stats:
    def __enter__(self):
        enable_operator_stats_collection()
        return self

    def __exit__(self, *exc):
        disable_operator_stats_collection()
        return False


def record_op(name, dtype):
    if _op_stats is None:
        return
    key = {"float32": "fp32", "float16": "fp16",
           "bfloat16": "bf16"}.get(str(dtype), "other")
    _op_stats[name][key] += 1
    _C_OP_CALLS[key].inc()
