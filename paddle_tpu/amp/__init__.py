"""`paddle.amp`: automatic mixed precision.

Parity: reference python/paddle/amp/ — `auto_cast` (auto_cast.py:1018)
O1/O2, `decorate` (:1103), `GradScaler` (grad_scaler.py:645) dynamic loss
scaling, allow/block op lists (amp_lists.py). TPU-first: bf16 is the native
mixed-precision dtype (MXU-preferred) and needs NO loss scaling — the
GradScaler surface is kept for fp16 parity and is an exact-passthrough for
bf16 (`use_dynamic_loss_scaling` effectively off), mirroring how the
reference disables scaling for bf16 (grad_scaler.py handles both).
"""

from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core import flags as flags_mod
from ..core.tensor import Tensor

__all__ = ["auto_cast", "autocast", "decorate", "GradScaler", "AmpScaler",
           "amp_state", "white_list", "black_list", "fp8"]


def __getattr__(name):  # lazy: fp8 pulls in nn at first use, not at init
    if name == "fp8":
        import importlib
        return importlib.import_module(".fp8", __name__)
    raise AttributeError(f"module 'paddle_tpu.amp' has no attribute "
                         f"{name!r}")

# op-name lists (reference amp_lists.py): ops routed to low precision vs
# kept in fp32. Consulted by core.dispatch during auto_cast.
white_list = {
    "matmul", "linear", "conv2d", "conv1d", "conv3d", "einsum", "bmm",
    "flash_attention", "mm",
}
black_list = {
    "exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
    "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "c_softmax_with_cross_entropy", "cross_entropy", "layer_norm",
    "log_softmax", "rms_norm", "batch_norm", "group_norm",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = dtype_mod.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def amp_state():
    return _state


def _cast_to(t, dt):
    if isinstance(t, Tensor) and dtype_mod.is_floating_point(t.dtype) and \
            t.dtype != dt:
        from ..ops import cast
        return cast(t, dt)
    return t


def amp_dispatch_pre(name, args):
    """Hook called by core.dispatch.apply when AMP is on: casts inputs of
    white-list ops to the AMP dtype, black-list ops to fp32 (O1
    semantics, mirroring the generated AMP_LOGIC_TEMPLATE in
    eager_gen.py:594)."""
    if not _state.enabled:
        return args
    wl = (white_list | _state.custom_white) - _state.custom_black
    bl = (black_list | _state.custom_black) - _state.custom_white
    if name in wl:
        return tuple(_cast_to(a, _state.dtype) for a in args)
    if name in bl:
        return tuple(_cast_to(a, dtype_mod.float32) for a in args)
    return args


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """reference auto_cast.py:1018. O1: per-op cast by lists. O2: the
    caller should also `decorate` the model to the AMP dtype."""
    prev = (_state.enabled, _state.dtype, _state.level,
            _state.custom_white, _state.custom_black)
    _state.enabled = enable
    _state.dtype = dtype_mod.convert_dtype(dtype)
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    # dispatch snapshots amp-enabled per settings epoch; bump AFTER the
    # state change so the very next op (warm call sites included)
    # observes the toggle — no stale-snapshot window
    flags_mod._bump_epoch()
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level,
         _state.custom_white, _state.custom_black) = prev
        flags_mod._bump_epoch()


autocast = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """reference auto_cast.py:1103: O2 casts model params to the AMP dtype
    (norm layers excluded) and turns on optimizer master weights."""
    from ..nn.layer.norm import _BatchNormBase, LayerNorm

    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        excluded = tuple(excluded_layers or ()) + (LayerNorm, _BatchNormBase)
        for model in model_list:
            for layer in model.sublayers(include_self=True):
                if isinstance(layer, excluded):
                    continue
                for p in layer._parameters.values():
                    if p is not None and dtype_mod.is_floating_point(p.dtype):
                        p._rebind(p._data.astype(
                            dtype_mod.convert_dtype(dtype)))
    if optimizers is None:
        return models if single else model_list
    opt_single = not isinstance(optimizers, (list, tuple))
    opt_list = [optimizers] if opt_single else list(optimizers)
    for opt in opt_list:
        opt._multi_precision = True
    return ((models if single else model_list),
            (optimizers if opt_single else opt_list))


class GradScaler:
    """reference grad_scaler.py:645. Dynamic loss scaling for fp16; for
    bf16 (or enable=False) scale/unscale are identity — the recommended
    TPU configuration."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling and enable
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # per-optimizer state machine READY -> UNSCALED -> STEPPED, reset by
        # update() (reference grad_scaler.py:358 OptimizerState): step()
        # must not re-unscale after an explicit unscale_(), and calling
        # unscale_() twice between updates is an error.
        self._opt_states = {}

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale, jnp.float32))

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        state = self._opt_states.get(id(optimizer))
        if state is not None and state[0] == "unscaled":
            raise RuntimeError(
                "unscale_() has already been called on this optimizer "
                "since the last update().")
        if state is not None and state[0] == "stepped":
            raise RuntimeError("unscale_() is being called after step().")
        inv = 1.0 / self._scale
        # Single found_inf scalar accumulated on-device across all grads,
        # synced to host ONCE (the reference fuses this into
        # check_finite_and_unscale; per-param bool() syncs serialize the
        # device pipeline).
        found = jnp.asarray(False)
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._data.astype(jnp.float32) * inv
            found = found | ~jnp.isfinite(g).all()
            p.grad._rebind(g.astype(p.grad.dtype))
        found = bool(found)
        # _found_inf ORs across all optimizers since the last update() (for
        # the scale adjustment); step() consults the per-optimizer verdict.
        self._found_inf = self._found_inf or found
        self._opt_states[id(optimizer)] = ("unscaled", found)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        state = self._opt_states.get(id(optimizer))
        if state is not None and state[0] == "stepped":
            raise RuntimeError(
                "step() has already been called since the last update().")
        if state is None:
            self.unscale_(optimizer)
        found = self._opt_states[id(optimizer)][1]
        if not found:
            optimizer.step()
        self._opt_states[id(optimizer)] = ("stepped", found)

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()
        optimizer.clear_grad()

    def update(self):
        self._update()

    def _update(self):
        self._opt_states.clear()
        found = self._found_inf
        self._found_inf = False
        if not self._dynamic:
            return
        if found:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every,
                "decr_every_n_nan_or_inf": self._decr_every,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)


AmpScaler = GradScaler


def is_bfloat16_supported(device=None):
    return True


def is_float16_supported(device=None):
    return True


class OptimizerState:
    """Reference amp/grad_scaler.py OptimizerState enum."""

    INIT = 0
    UNSCALED = 1
    STEPPED = 2


# legacy-name aliases (reference amp/__init__.py re-exports)
amp_guard = auto_cast
amp_decorate = decorate
