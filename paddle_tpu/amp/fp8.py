"""FP8 training: scaled fp8 matmul + delayed scaling + layer wiring.

Capability parity with the reference's fp8 GEMM path
(`paddle/phi/kernels/fusion/fp8_gemm/fp8_gemm_with_cublasLt/` over
`paddle/phi/common/float8_e4m3fn.h:1`), redesigned TPU-first:

- the "fp8 GEMM kernel" is `jax.lax.dot_general` on `float8_e4m3fn`
  operands with f32 accumulation — XLA lowers it to the MXU's native fp8
  path on TPU generations that have one and to convert+bf16-dot
  otherwise, so the same program is portable across v5e/v6;
- scaling follows the standard transformer-fp8 recipe: e4m3 for
  activations/weights (range ±448), e5m2 for gradients (range ±57344);
  **delayed scaling** for forward tensors (per-tensor amax history of
  `history_len` steps, scale = rolling-max amax / dtype_max) and
  **current scaling** for gradients (amax computed on the cotangent
  inside the backward itself — no cross-step gradient state);
- everything is traced: amax reductions and history rolls are jnp ops,
  so the whole fp8 step compiles into the one donated train-step
  executable (cross-lowered for TPU by tools/tpu_lowering_gate.py).

Opt-in wiring: ``convert_to_fp8(model)`` swaps ``nn.Linear`` layers for
``FP8Linear`` in place (same Parameter objects), or build models with
``use_fp8=True`` (GPT/Llama configs). ``fp8_autocast(enabled=False)``
temporarily demotes converted layers back to the plain bf16 path.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = [
    "DelayedScaling", "FP8Linear", "convert_to_fp8", "fp8_autocast",
    "scaled_fp8_matmul", "fp8_white_list", "E4M3_MAX", "E5M2_MAX",
]

E4M3_MAX = 448.0
E5M2_MAX = 57344.0

# parity surface with amp.white_list: the op names with an fp8 compute
# path today (scaled_fp8_matmul / FP8Linear). Informational — dispatch is
# opt-in via convert_to_fp8/FP8Linear, not list-driven.
fp8_white_list = {"matmul", "linear", "mm", "bmm"}


@dataclasses.dataclass
class DelayedScaling:
    """Scaling recipe (the reference's per-tensor scale/amax bookkeeping
    around its cublasLt fp8 GEMM, as data): forward scales derive from a
    rolling amax history; gradient scales are computed on the fly."""

    margin: int = 0            # scale = amax * 2**margin / dtype_max
    amax_history_len: int = 16
    amax_compute_algo: str = "max"  # "max" | "most_recent"


class _FP8State(threading.local):
    def __init__(self):
        self.override = None  # None: layer default; False: force off
        self.recipe = None    # scope recipe override


_state = _FP8State()


@contextlib.contextmanager
def fp8_autocast(enabled=True, recipe=None):
    """Scope-gate converted FP8 layers (TransformerEngine-style surface).
    ``enabled=False`` runs them as plain linears; ``recipe`` overrides the
    layer recipe inside the scope (affects newly computed scales only)."""
    prev = (_state.override, _state.recipe)
    _state.override = bool(enabled)
    _state.recipe = recipe
    try:
        yield
    finally:
        _state.override, _state.recipe = prev


def fp8_enabled(layer_default=True):
    return layer_default if _state.override is None else _state.override


def _quantize(x, scale, fp8_max, dtype):
    inv = 1.0 / scale
    return jnp.clip(x.astype(jnp.float32) * inv,
                    -fp8_max, fp8_max).astype(dtype)


@jax.custom_vjp
def _scaled_mm(x2d, w, sx, sw):
    """[M,K]@[K,N] with e4m3 operands; f32 accumulation; returns f32."""
    xq = _quantize(x2d, sx, E4M3_MAX, jnp.float8_e4m3fn)
    wq = _quantize(w, sw, E4M3_MAX, jnp.float8_e4m3fn)
    y = jax.lax.dot_general(xq, wq, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return y * (sx * sw)


def _scaled_mm_fwd(x2d, w, sx, sw):
    xq = _quantize(x2d, sx, E4M3_MAX, jnp.float8_e4m3fn)
    wq = _quantize(w, sw, E4M3_MAX, jnp.float8_e4m3fn)
    y = jax.lax.dot_general(xq, wq, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    # zero-size dtype carriers: the bwd rule must emit cotangents in the
    # PRIMAL dtypes (bf16 params -> bf16 grads) or the f32 grads leak up
    # the tape and the upstream vjp_fn rejects them (caught on the v5e
    # bf16 345M fp8 bench rung)
    xp = jnp.zeros((0,), x2d.dtype)
    wp = jnp.zeros((0,), w.dtype)
    return y * (sx * sw), (xq, wq, sx, sw, xp, wp)


def _scaled_mm_bwd(res, g):
    xq, wq, sx, sw, xp, wp = res
    g32 = g.astype(jnp.float32)
    # current scaling for the cotangent: e5m2 (wide range, the fp8 grad
    # dtype the reference uses on the cublasLt path as well)
    sg = jnp.maximum(jnp.max(jnp.abs(g32)), 1e-12) / E5M2_MAX
    gq = _quantize(g32, sg, E5M2_MAX, jnp.float8_e5m2)
    # dx = g @ w^T ; dw = x^T @ g — both as fp8 GEMMs, f32 accumulation
    dx = jax.lax.dot_general(gq, wq, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dw = jax.lax.dot_general(xq, gq, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    return ((dx * (sg * sw)).astype(xp.dtype),
            (dw * (sx * sg)).astype(wp.dtype),
            jnp.zeros_like(sx), jnp.zeros_like(sw))


_scaled_mm.defvjp(_scaled_mm_fwd, _scaled_mm_bwd)


def _fp8_linear_fn(x, w, b, sx, sw):
    """apply()-dispatched op: flatten batch dims, fp8 matmul, bias add."""
    lead = x.shape[:-1]
    x2d = x.reshape((-1, x.shape[-1]))
    y = _scaled_mm(x2d, w, sx, sw)
    y = y.reshape(lead + (w.shape[-1],)).astype(x.dtype)
    if b is not None:
        y = y + b
    return y


def _fp8_matmul_fn(x, y, sx, sy):
    lead = x.shape[:-1]
    x2d = x.reshape((-1, x.shape[-1]))
    out = _scaled_mm(x2d, y, sx, sy)
    return out.reshape(lead + (y.shape[-1],)).astype(x.dtype)


def scaled_fp8_matmul(x, y, x_scale=None, y_scale=None, name=None):
    """Functional scaled fp8 matmul on Tensors: ``x @ y`` with e4m3
    operands / f32 accumulation / e5m2 current-scaled gradients. Scales
    default to current amax/E4M3_MAX."""
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    ya = y._data if isinstance(y, Tensor) else jnp.asarray(y)
    sx = (jnp.maximum(jnp.max(jnp.abs(xa.astype(jnp.float32))), 1e-12)
          / E4M3_MAX) if x_scale is None else jnp.asarray(x_scale,
                                                          jnp.float32)
    sy = (jnp.maximum(jnp.max(jnp.abs(ya.astype(jnp.float32))), 1e-12)
          / E4M3_MAX) if y_scale is None else jnp.asarray(y_scale,
                                                          jnp.float32)
    # pass the converted arrays (not the raw inputs): list/np inputs must
    # reach _fp8_matmul_fn as arrays with .shape
    return apply(_fp8_matmul_fn,
                 x if isinstance(x, Tensor) else xa,
                 y if isinstance(y, Tensor) else ya,
                 sx, sy, name="fp8_matmul")


def _delayed_scale(history, cur_amax, fp8_max, margin, algo):
    """Scale for THIS step from the history (before cur is rolled in);
    zero history (startup) falls back to the current amax."""
    amax = (history[0] if algo == "most_recent" else jnp.max(history))
    amax = jnp.where(amax > 0.0, amax, cur_amax)
    amax = jnp.maximum(amax, 1e-12)
    return amax * np.float32(2.0 ** margin) / np.float32(fp8_max)


_FP8LinearCls = None


def _fp8_linear_cls():
    """Single FP8Linear class, created lazily (amp must not import nn at
    module load — package init order)."""
    global _FP8LinearCls
    if _FP8LinearCls is not None:
        return _FP8LinearCls
    from .. import nn

    class FP8Linear(nn.Linear):
        """Drop-in fp8 replacement for nn.Linear: same parameters, fp8
        compute, delayed-scaling buffers (`fp8_amax_x/w` history,
        `fp8_scale_x/w` for observability/checkpointing)."""

        def __init__(self, in_features, out_features, weight_attr=None,
                     bias_attr=None, recipe=None, name=None):
            super().__init__(in_features, out_features,
                             weight_attr=weight_attr, bias_attr=bias_attr)
            _init_fp8_state(self, recipe)

        def forward(self, x):
            from ..nn import functional as F
            if not fp8_enabled():
                return F.linear(x, self.weight, self.bias)
            recipe = _state.recipe or self.fp8_recipe
            xa = x._data
            wa = self.weight._data
            # amax/scale bookkeeping stays OFF the tape (scales are
            # constants of the linearization, as in the reference recipe)
            cur_x = jnp.max(jnp.abs(xa.astype(jnp.float32)))
            cur_w = jnp.max(jnp.abs(wa.astype(jnp.float32)))
            hx = self.fp8_amax_x._data
            hw = self.fp8_amax_w._data
            sx = _delayed_scale(hx, cur_x, E4M3_MAX, recipe.margin,
                                recipe.amax_compute_algo)
            sw = _delayed_scale(hw, cur_w, E4M3_MAX, recipe.margin,
                                recipe.amax_compute_algo)
            if self.training:
                self.fp8_amax_x._rebind(
                    jnp.concatenate([cur_x[None], hx[:-1]]))
                self.fp8_amax_w._rebind(
                    jnp.concatenate([cur_w[None], hw[:-1]]))
                self.fp8_scale_x._rebind(sx)
                self.fp8_scale_w._rebind(sw)
            bias = self.bias
            if bias is not None:
                return apply(_fp8_linear_fn, x, self.weight, bias, sx, sw,
                             name="fp8_linear")
            return apply(_fp8_linear_fn, x, self.weight, None, sx, sw,
                         name="fp8_linear")

    _FP8LinearCls = FP8Linear
    return FP8Linear


def __getattr__(name):  # PEP 562: fp8.FP8Linear without import cycles
    if name == "FP8Linear":
        return _fp8_linear_cls()
    raise AttributeError(name)


def _init_fp8_state(layer, recipe):
    layer.fp8_recipe = recipe or DelayedScaling()
    h = layer.fp8_recipe.amax_history_len
    layer.register_buffer("fp8_amax_x", Tensor(jnp.zeros((h,), jnp.float32)))
    layer.register_buffer("fp8_amax_w", Tensor(jnp.zeros((h,), jnp.float32)))
    layer.register_buffer("fp8_scale_x", Tensor(jnp.ones((), jnp.float32)))
    layer.register_buffer("fp8_scale_w", Tensor(jnp.ones((), jnp.float32)))


def convert_to_fp8(model, recipe=None, include=None, exclude=()):
    """Swap every ``nn.Linear`` under ``model`` for an FP8Linear IN PLACE,
    keeping the existing weight/bias Parameter objects (placements,
    optimizer registration, and checkpoints stay valid).

    ``include``: optional predicate/name-list restricting conversion;
    ``exclude``: name substrings to skip (e.g. ``("lm_head",)`` — the
    final projection usually stays bf16 for loss fidelity, matching
    standard fp8 transformer recipes).
    """
    from .. import nn

    def want(name):
        if any(e in name for e in exclude):
            return False
        if include is None:
            return True
        if callable(include):
            return include(name)
        return any(i in name for i in include)

    cls = _fp8_linear_cls()

    def walk(layer, prefix=""):
        for name, sub in list(layer.named_children()):
            full = f"{prefix}.{name}" if prefix else name
            if type(sub) is nn.Linear and want(full):
                # re-class in place: same object, same Parameter objects
                # (optimizer registration, placements, checkpoints stay
                # valid)
                sub.__class__ = cls
                _init_fp8_state(sub, recipe)
            else:
                walk(sub, full)
    walk(model)
    return model
