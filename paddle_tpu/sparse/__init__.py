"""`paddle.sparse` (reference: python/paddle/sparse/ over
SparseCooTensor/SparseCsrTensor, paddle/phi/core/sparse_coo_tensor.h).

TPU-first: COO tensors wrap `jax.experimental.sparse.BCOO` — XLA lowers
scatter/gather/spmm natively; CSR keeps (crows, cols, values) and
converts through COO for compute.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.dispatch import unwrap
from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "add", "matmul", "masked_matmul", "mv",
           "relu", "to_dense", "is_same_shape", "nn", "transpose",
           "sin", "sinh", "asin", "asinh", "tan", "tanh", "atan", "atanh",
           "sqrt", "square", "log1p", "expm1", "abs", "neg", "deg2rad",
           "rad2deg", "isnan", "pow", "cast", "coalesce", "subtract",
           "multiply", "divide", "sum", "reshape", "slice", "mask_as",
           "pca_lowrank"]


class SparseCooTensor:
    def __init__(self, bcoo, shape=None):
        self._bcoo = bcoo
        self._shape = list(shape or bcoo.shape)

    @property
    def shape(self):
        return list(self._shape)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self):
        return Tensor(self._bcoo.data)

    @property
    def dtype(self):
        return self._bcoo.data.dtype

    def nnz(self):
        return int(self._bcoo.nse)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self):
        coo = self._bcoo.sum_duplicates()
        idx = np.asarray(coo.indices)
        vals = np.asarray(coo.data)
        order = np.lexsort((idx[:, 1], idx[:, 0]))
        rows, cols = idx[order, 0], idx[order, 1]
        n_rows = self._shape[0]
        crows = np.zeros(n_rows + 1, np.int32)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows).astype(np.int32)
        return SparseCsrTensor(crows, cols.astype(np.int32), vals[order],
                               self._shape)

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates(), self._shape)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self._shape}, "
                f"nnz={self.nnz()})")


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self.crows_arr = jnp.asarray(unwrap(crows), jnp.int32)
        self.cols_arr = jnp.asarray(unwrap(cols), jnp.int32)
        self.values_arr = jnp.asarray(unwrap(values))
        self._shape = list(shape)

    @property
    def shape(self):
        return list(self._shape)

    def crows(self):
        return Tensor(self.crows_arr)

    def cols(self):
        return Tensor(self.cols_arr)

    def values(self):
        return Tensor(self.values_arr)

    def nnz(self):
        return int(self.values_arr.shape[0])

    def to_dense(self):
        n_rows = self._shape[0]
        counts = self.crows_arr[1:] - self.crows_arr[:-1]
        rows = jnp.repeat(jnp.arange(n_rows), counts,
                          total_repeat_length=self.nnz())
        dense = jnp.zeros(self._shape, self.values_arr.dtype)
        return Tensor(dense.at[rows, self.cols_arr].add(self.values_arr))

    def to_sparse_coo(self, sparse_dim=2):
        n_rows = self._shape[0]
        counts = self.crows_arr[1:] - self.crows_arr[:-1]
        rows = jnp.repeat(jnp.arange(n_rows), counts,
                          total_repeat_length=self.nnz())
        idx = jnp.stack([rows, self.cols_arr], axis=1)
        bcoo = jsparse.BCOO((self.values_arr, idx), shape=tuple(self._shape))
        return SparseCooTensor(bcoo)

    def __repr__(self):
        return f"SparseCsrTensor(shape={self._shape}, nnz={self.nnz()})"


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    idx = jnp.asarray(unwrap(indices), jnp.int32)
    vals = jnp.asarray(unwrap(values))
    if dtype is not None:
        from ..core import dtype as dtype_mod
        vals = vals.astype(dtype_mod.convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    bcoo = jsparse.BCOO((vals, jnp.swapaxes(idx, 0, 1)),
                        shape=tuple(shape))
    return SparseCooTensor(bcoo, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape)


def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


def to_dense(x):
    return x.to_dense() if isinstance(
        x, (SparseCooTensor, SparseCsrTensor)) else x


def add(x, y):
    x, y = _coo(x), _coo(y)
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        data = jnp.concatenate([x._bcoo.data, y._bcoo.data])
        idx = jnp.concatenate([x._bcoo.indices, y._bcoo.indices])
        return SparseCooTensor(
            jsparse.BCOO((data, idx), shape=tuple(x._shape))
            .sum_duplicates(), x._shape)
    return Tensor(to_dense(x)._data + to_dense(y)._data)


def matmul(x, y):
    """sparse @ dense (reference paddle.sparse.matmul)."""
    x = _coo(x)
    y_arr = unwrap(y)
    if isinstance(x, SparseCooTensor):
        return Tensor(x._bcoo @ y_arr)
    return Tensor(unwrap(x) @ y_arr)


def mv(x, vec):
    return matmul(x, vec)


def masked_matmul(x, y, mask):
    """dense @ dense evaluated only at mask's sparsity pattern."""
    out = unwrap(x) @ unwrap(y)
    m = _coo(mask)
    idx = m._bcoo.indices
    vals = out[idx[:, 0], idx[:, 1]]
    return SparseCooTensor(jsparse.BCOO((vals, idx),
                                        shape=tuple(m._shape)), m._shape)


def relu(x):
    x = _coo(x)
    return SparseCooTensor(
        jsparse.BCOO((jnp.maximum(x._bcoo.data, 0), x._bcoo.indices),
                     shape=tuple(x._shape)), x._shape)


def transpose(x, perm):
    x = _coo(x)
    idx = x._bcoo.indices[:, jnp.asarray(perm)]
    shape = [x._shape[p] for p in perm]
    return SparseCooTensor(jsparse.BCOO((x._bcoo.data, idx),
                                        shape=tuple(shape)), shape)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


class _SparseNN:
    @staticmethod
    def ReLU():
        class _R:
            def __call__(self, x):
                return relu(x)
        return _R()


nn = _SparseNN()


# ---------------------------------------------------------------------------
# elementwise value ops (reference python/paddle/sparse/unary.py /
# binary.py: each applies to the stored values, preserving sparsity)
# ---------------------------------------------------------------------------

def _unary_valueop(fn, name):
    def op(x, *args, **kwargs):
        c = _coo(x)
        return SparseCooTensor(
            jsparse.BCOO((fn(c._bcoo.data, *args, **kwargs),
                          c._bcoo.indices), shape=tuple(c._shape)),
            c._shape)
    op.__name__ = name
    return op


sin = _unary_valueop(jnp.sin, "sin")
sinh = _unary_valueop(jnp.sinh, "sinh")
asin = _unary_valueop(jnp.arcsin, "asin")
asinh = _unary_valueop(jnp.arcsinh, "asinh")
tan = _unary_valueop(jnp.tan, "tan")
tanh = _unary_valueop(jnp.tanh, "tanh")
atan = _unary_valueop(jnp.arctan, "atan")
atanh = _unary_valueop(jnp.arctanh, "atanh")
sqrt = _unary_valueop(jnp.sqrt, "sqrt")
square = _unary_valueop(jnp.square, "square")
log1p = _unary_valueop(jnp.log1p, "log1p")
expm1 = _unary_valueop(jnp.expm1, "expm1")
abs = _unary_valueop(jnp.abs, "abs")  # noqa: A001
neg = _unary_valueop(jnp.negative, "neg")
deg2rad = _unary_valueop(jnp.deg2rad, "deg2rad")
rad2deg = _unary_valueop(jnp.rad2deg, "rad2deg")
isnan = _unary_valueop(jnp.isnan, "isnan")


def pow(x, factor):  # noqa: A001
    return _unary_valueop(lambda v: jnp.power(v, factor), "pow")(x)


def cast(x, index_dtype=None, value_dtype=None):
    c = _coo(x)
    data = c._bcoo.data if value_dtype is None else \
        c._bcoo.data.astype(value_dtype)
    idx = c._bcoo.indices if index_dtype is None else \
        c._bcoo.indices.astype(index_dtype)
    return SparseCooTensor(jsparse.BCOO((data, idx),
                                        shape=tuple(c._shape)), c._shape)


def coalesce(x):
    return _coo(x).coalesce()


def _binary_valueop(fn, name):
    def op(x, y):
        a = _coo(x).coalesce()
        b = _coo(y).coalesce()
        # dense-side combine keeps semantics exact for mismatched patterns
        dense = fn(a._bcoo.todense(), b._bcoo.todense())
        return SparseCooTensor(jsparse.BCOO.fromdense(dense),
                               list(dense.shape))
    op.__name__ = name
    return op


subtract = _binary_valueop(jnp.subtract, "subtract")
multiply = _binary_valueop(jnp.multiply, "multiply")
divide = _binary_valueop(jnp.divide, "divide")


def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    c = _coo(x)
    out = jnp.sum(c._bcoo.todense(), axis=axis, dtype=dtype,
                  keepdims=keepdim)
    return Tensor(out)


def reshape(x, shape):
    c = _coo(x).coalesce()
    dense = c._bcoo.todense().reshape(shape)
    return SparseCooTensor(jsparse.BCOO.fromdense(dense),
                           list(dense.shape))


def slice(x, axes, starts, ends):  # noqa: A001
    c = _coo(x).coalesce()
    dense = c._bcoo.todense()
    import builtins
    idx = [builtins.slice(None)] * dense.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = builtins.slice(int(st), int(en))
    out = dense[tuple(idx)]
    return SparseCooTensor(jsparse.BCOO.fromdense(out), list(out.shape))


def mask_as(x, mask):
    """Keep x's dense values at mask's sparsity pattern (reference
    sparse/multiary.py mask_as)."""
    m = _coo(mask).coalesce()
    dense = unwrap(x) if not isinstance(x, SparseCooTensor) else \
        x._bcoo.todense()
    vals = dense[tuple(m._bcoo.indices.T)]
    return SparseCooTensor(jsparse.BCOO((vals, m._bcoo.indices),
                                        shape=tuple(m._shape)), m._shape)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """PCA on a sparse matrix (reference paddle.sparse.pca_lowrank):
    densify + the shared lowrank path."""
    from ..ops.special import pca_lowrank as _dense_pca
    dense = Tensor(_coo(x)._bcoo.todense()) \
        if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x
    return _dense_pca(dense, q=q, center=center, niter=niter)
