"""`paddle.sparse` (reference: python/paddle/sparse/ over
SparseCooTensor/SparseCsrTensor, paddle/phi/core/sparse_coo_tensor.h).

TPU-first: COO tensors wrap `jax.experimental.sparse.BCOO` — XLA lowers
scatter/gather/spmm natively; CSR keeps (crows, cols, values) and
converts through COO for compute. The stored values additionally travel
as an eager-tape `Tensor` (`_vt`), so sparse conv/norm/activation chains
backpropagate end-to-end (reference: sparse grad kernels under
paddle/phi/kernels/sparse/).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.dispatch import unwrap
from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "add", "addmm", "matmul", "masked_matmul",
           "mv", "relu", "to_dense", "is_same_shape", "nn", "transpose",
           "sin", "sinh", "asin", "asinh", "tan", "tanh", "atan", "atanh",
           "sqrt", "square", "log1p", "expm1", "abs", "neg", "deg2rad",
           "rad2deg", "isnan", "pow", "cast", "coalesce", "subtract",
           "multiply", "divide", "sum", "reshape", "slice", "mask_as",
           "pca_lowrank"]


class SparseCooTensor:
    def __init__(self, bcoo, shape=None, values_tensor=None):
        self._bcoo = bcoo
        self._shape = list(shape or bcoo.shape)
        # tape-linked view of the stored values (grads flow through it)
        self._vt = values_tensor if values_tensor is not None \
            else Tensor(bcoo.data)

    @property
    def shape(self):
        return list(self._shape)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self):
        return self._vt

    @property
    def dtype(self):
        return self._bcoo.data.dtype

    def nnz(self):
        return int(self._bcoo.nse)

    def to_dense(self):
        from ..core.dispatch import apply
        idx = self._bcoo.indices
        shape = tuple(self._shape)

        def scatter(v):
            dense = jnp.zeros(shape, v.dtype)
            return dense.at[tuple(idx.T)].add(v)

        return apply(scatter, self._vt, name="sparse_to_dense")

    def to_sparse_csr(self):
        coo = self._bcoo.sum_duplicates()
        idx = np.asarray(coo.indices)
        vals = np.asarray(coo.data)
        order = np.lexsort((idx[:, 1], idx[:, 0]))
        rows, cols = idx[order, 0], idx[order, 1]
        n_rows = self._shape[0]
        crows = np.zeros(n_rows + 1, np.int32)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows).astype(np.int32)
        return SparseCsrTensor(crows, cols.astype(np.int32), vals[order],
                               self._shape)

    def coalesce(self):
        """Sum duplicate coordinates; keeps the values' tape link (the
        duplicate reduction is a recorded segment_sum, and the no-dup case
        returns self unchanged)."""
        idx = np.asarray(jax.device_get(self._bcoo.indices))
        uniq, inv = np.unique(idx, axis=0, return_inverse=True)
        if uniq.shape[0] == idx.shape[0]:
            return self
        from ..core.dispatch import apply
        seg = jnp.asarray(inv.reshape(-1), jnp.int32)
        n = uniq.shape[0]
        vt = apply(lambda v: jax.ops.segment_sum(v, seg, num_segments=n),
                   self._vt, name="sparse_coalesce")
        return _make_coo(vt, jnp.asarray(uniq, self._bcoo.indices.dtype),
                         self._shape)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self._shape}, "
                f"nnz={self.nnz()})")


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape, _values_tensor=None):
        self.crows_arr = jnp.asarray(unwrap(crows), jnp.int32)
        self.cols_arr = jnp.asarray(unwrap(cols), jnp.int32)
        self.values_arr = jnp.asarray(unwrap(values))
        self._vt = _values_tensor if _values_tensor is not None \
            else Tensor(self.values_arr)
        self._shape = list(shape)

    @property
    def shape(self):
        return list(self._shape)

    def crows(self):
        return Tensor(self.crows_arr)

    def cols(self):
        return Tensor(self.cols_arr)

    def values(self):
        return self._vt

    def nnz(self):
        return int(self.values_arr.shape[0])

    def to_dense(self):
        n_rows = self._shape[0]
        counts = self.crows_arr[1:] - self.crows_arr[:-1]
        rows = jnp.repeat(jnp.arange(n_rows), counts,
                          total_repeat_length=self.nnz())
        dense = jnp.zeros(self._shape, self.values_arr.dtype)
        return Tensor(dense.at[rows, self.cols_arr].add(self.values_arr))

    def to_sparse_coo(self, sparse_dim=2):
        n_rows = self._shape[0]
        counts = self.crows_arr[1:] - self.crows_arr[:-1]
        rows = jnp.repeat(jnp.arange(n_rows), counts,
                          total_repeat_length=self.nnz())
        idx = jnp.stack([rows, self.cols_arr], axis=1)
        bcoo = jsparse.BCOO((self.values_arr, idx), shape=tuple(self._shape))
        return SparseCooTensor(bcoo, values_tensor=self._vt)

    def __repr__(self):
        return f"SparseCsrTensor(shape={self._shape}, nnz={self.nnz()})"


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    idx = jnp.asarray(unwrap(indices), jnp.int32)
    vt = values if isinstance(values, Tensor) else None
    vals = jnp.asarray(unwrap(values))
    if dtype is not None:
        from ..core import dtype as dtype_mod
        target = dtype_mod.convert_dtype(dtype)
        if vals.dtype != target:
            if vt is not None:
                from .. import ops
                vt = ops.cast(vt, dtype)
                vals = vt._data
            else:
                vals = vals.astype(target)
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    bcoo = jsparse.BCOO((vals, jnp.swapaxes(idx, 0, 1)),
                        shape=tuple(shape))
    return SparseCooTensor(bcoo, shape, values_tensor=vt)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape)


def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


def _make_coo(values_tensor, indices, shape):
    """Build a SparseCooTensor whose values keep their tape link."""
    bcoo = jsparse.BCOO((values_tensor._data, indices), shape=tuple(shape))
    return SparseCooTensor(bcoo, list(shape), values_tensor=values_tensor)


def to_dense(x):
    return x.to_dense() if isinstance(
        x, (SparseCooTensor, SparseCsrTensor)) else x


def add(x, y):
    x, y = _coo(x), _coo(y)
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        data = jnp.concatenate([x._bcoo.data, y._bcoo.data])
        idx = jnp.concatenate([x._bcoo.indices, y._bcoo.indices])
        return SparseCooTensor(
            jsparse.BCOO((data, idx), shape=tuple(x._shape))
            .sum_duplicates(), x._shape)
    return Tensor(to_dense(x)._data + to_dense(y)._data)


def matmul(x, y):
    """sparse @ dense (reference paddle.sparse.matmul)."""
    from ..core.dispatch import apply
    x = _coo(x)
    if isinstance(x, SparseCooTensor):
        idx, shape = x._bcoo.indices, tuple(x._shape)
        yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(unwrap(y)))
        return apply(
            lambda v, ya: jsparse.BCOO((v, idx), shape=shape) @ ya,
            x.values(), yt, name="sparse_matmul")
    return Tensor(unwrap(x) @ unwrap(y))


def mv(x, vec):
    return matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    """beta * input + alpha * (x @ y) (reference
    python/paddle/sparse/multiary.py:29). Two layouts, like the
    reference: dense input + sparse x + dense y -> dense; all-sparse
    (COO or CSR) -> sparse of the same format."""
    from .. import ops
    from ..core.dispatch import apply
    if not isinstance(input, (SparseCooTensor, SparseCsrTensor)):
        prod = matmul(x, y)
        inp = input if isinstance(input, Tensor) else Tensor(
            jnp.asarray(unwrap(input)))
        return ops.add(ops.scale(inp, beta), ops.scale(prod, alpha))

    want_csr = isinstance(input, SparseCsrTensor)
    ic, xc, yc = _coo(input).coalesce(), _coo(x).coalesce(), \
        _coo(y).coalesce()
    i_idx, x_idx, y_idx = (t._bcoo.indices for t in (ic, xc, yc))
    shape = tuple(ic._shape)
    xshape, yshape = tuple(xc._shape), tuple(yc._shape)

    def dense_out(iv, xv, yv):
        di = jnp.zeros(shape, iv.dtype).at[tuple(i_idx.T)].add(iv)
        dx = jnp.zeros(xshape, xv.dtype).at[tuple(x_idx.T)].add(xv)
        dy = jnp.zeros(yshape, yv.dtype).at[tuple(y_idx.T)].add(yv)
        return beta * di + alpha * (dx @ dy)

    eager = np.asarray(jax.device_get(
        dense_out(ic._vt._data, xc._vt._data, yc._vt._data)))
    nz = np.argwhere(eager != 0)  # lexicographic = CSR row-major order
    idx = jnp.asarray(nz, jnp.int32)
    vt = apply(lambda iv, xv, yv: dense_out(iv, xv, yv)[tuple(idx.T)],
               ic._vt, xc._vt, yc._vt, name="sparse_addmm")
    if not want_csr:
        return _make_coo(vt, idx, list(shape))
    counts = np.zeros(shape[0] + 1, np.int64)
    np.add.at(counts, nz[:, 0] + 1, 1)
    return SparseCsrTensor(np.cumsum(counts).astype(np.int32),
                           nz[:, 1].astype(np.int32), vt._data,
                           list(shape), _values_tensor=vt)


def masked_matmul(x, y, mask):
    """dense @ dense evaluated only at mask's sparsity pattern."""
    from ..core.dispatch import apply
    m = _coo(mask)
    idx = m._bcoo.indices
    xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(unwrap(x)))
    yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(unwrap(y)))
    vt = apply(lambda xa, ya: (xa @ ya)[idx[:, 0], idx[:, 1]], xt, yt,
               name="sparse_masked_matmul")
    return _make_coo(vt, idx, m._shape)


def relu(x):
    from .nn import functional as _F
    return _F.relu(x)


def transpose(x, perm):
    x = _coo(x)
    idx = x._bcoo.indices[:, jnp.asarray(perm)]
    shape = [x._shape[p] for p in perm]
    return SparseCooTensor(jsparse.BCOO((x._bcoo.data, idx),
                                        shape=tuple(shape)), shape)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


# ---------------------------------------------------------------------------
# elementwise value ops (reference python/paddle/sparse/unary.py /
# binary.py: each applies to the stored values, preserving sparsity)
# ---------------------------------------------------------------------------

def _unary_valueop(fn, name):
    def op(x, *args, **kwargs):
        from ..core.dispatch import apply
        c = _coo(x)
        vt = apply(lambda v: fn(v, *args, **kwargs), c.values(),
                   name=f"sparse_{name}")
        return _make_coo(vt, c._bcoo.indices, c._shape)
    op.__name__ = name
    return op


sin = _unary_valueop(jnp.sin, "sin")
sinh = _unary_valueop(jnp.sinh, "sinh")
asin = _unary_valueop(jnp.arcsin, "asin")
asinh = _unary_valueop(jnp.arcsinh, "asinh")
tan = _unary_valueop(jnp.tan, "tan")
tanh = _unary_valueop(jnp.tanh, "tanh")
atan = _unary_valueop(jnp.arctan, "atan")
atanh = _unary_valueop(jnp.arctanh, "atanh")
sqrt = _unary_valueop(jnp.sqrt, "sqrt")
square = _unary_valueop(jnp.square, "square")
log1p = _unary_valueop(jnp.log1p, "log1p")
expm1 = _unary_valueop(jnp.expm1, "expm1")
abs = _unary_valueop(jnp.abs, "abs")  # noqa: A001
neg = _unary_valueop(jnp.negative, "neg")
deg2rad = _unary_valueop(jnp.deg2rad, "deg2rad")
rad2deg = _unary_valueop(jnp.rad2deg, "rad2deg")
isnan = _unary_valueop(jnp.isnan, "isnan")


def pow(x, factor):  # noqa: A001
    return _unary_valueop(lambda v: jnp.power(v, factor), "pow")(x)


def cast(x, index_dtype=None, value_dtype=None):
    c = _coo(x)
    data = c._bcoo.data if value_dtype is None else \
        c._bcoo.data.astype(value_dtype)
    idx = c._bcoo.indices if index_dtype is None else \
        c._bcoo.indices.astype(index_dtype)
    return SparseCooTensor(jsparse.BCOO((data, idx),
                                        shape=tuple(c._shape)), c._shape)


def coalesce(x):
    return _coo(x).coalesce()


def _binary_valueop(fn, name):
    def op(x, y):
        a = _coo(x).coalesce()
        b = _coo(y).coalesce()
        # dense-side combine keeps semantics exact for mismatched patterns
        dense = fn(a._bcoo.todense(), b._bcoo.todense())
        return SparseCooTensor(jsparse.BCOO.fromdense(dense),
                               list(dense.shape))
    op.__name__ = name
    return op


subtract = _binary_valueop(jnp.subtract, "subtract")
multiply = _binary_valueop(jnp.multiply, "multiply")
divide = _binary_valueop(jnp.divide, "divide")


def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    c = _coo(x)
    out = jnp.sum(c._bcoo.todense(), axis=axis, dtype=dtype,
                  keepdims=keepdim)
    return Tensor(out)


def reshape(x, shape):
    c = _coo(x).coalesce()
    dense = c._bcoo.todense().reshape(shape)
    return SparseCooTensor(jsparse.BCOO.fromdense(dense),
                           list(dense.shape))


def slice(x, axes, starts, ends):  # noqa: A001
    c = _coo(x).coalesce()
    dense = c._bcoo.todense()
    import builtins
    idx = [builtins.slice(None)] * dense.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = builtins.slice(int(st), int(en))
    out = dense[tuple(idx)]
    return SparseCooTensor(jsparse.BCOO.fromdense(out), list(out.shape))


def mask_as(x, mask):
    """Keep x's dense values at mask's sparsity pattern (reference
    sparse/multiary.py mask_as)."""
    from ..core.dispatch import apply
    m = _coo(mask).coalesce()
    idx = m._bcoo.indices
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        xd = to_dense(x)
    else:
        xd = x if isinstance(x, Tensor) else Tensor(jnp.asarray(unwrap(x)))
    vt = apply(lambda d: d[tuple(idx.T)], xd, name="sparse_mask_as")
    return _make_coo(vt, idx, m._shape)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """PCA on a sparse matrix (reference paddle.sparse.pca_lowrank):
    densify + the shared lowrank path."""
    from ..ops.special import pca_lowrank as _dense_pca
    dense = Tensor(_coo(x)._bcoo.todense()) \
        if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x
    return _dense_pca(dense, q=q, center=center, niter=niter)


from . import nn  # noqa: E402  (layer/functional subpackage)
