"""`paddle.sparse` (reference: python/paddle/sparse/ over
SparseCooTensor/SparseCsrTensor, paddle/phi/core/sparse_coo_tensor.h).

TPU-first: COO tensors wrap `jax.experimental.sparse.BCOO` — XLA lowers
scatter/gather/spmm natively; CSR keeps (crows, cols, values) and
converts through COO for compute. The stored values additionally travel
as an eager-tape `Tensor` (`_vt`), so sparse conv/norm/activation chains
backpropagate end-to-end (reference: sparse grad kernels under
paddle/phi/kernels/sparse/).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.dispatch import unwrap
from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "add", "addmm", "matmul", "masked_matmul",
           "mv", "relu", "to_dense", "is_same_shape", "nn", "transpose",
           "sin", "sinh", "asin", "asinh", "tan", "tanh", "atan", "atanh",
           "sqrt", "square", "log1p", "expm1", "abs", "neg", "deg2rad",
           "rad2deg", "isnan", "pow", "cast", "coalesce", "subtract",
           "multiply", "divide", "sum", "reshape", "slice", "mask_as",
           "pca_lowrank"]


class SparseCooTensor:
    def __init__(self, bcoo, shape=None, values_tensor=None):
        self._bcoo = bcoo
        self._shape = list(shape or bcoo.shape)
        # tape-linked view of the stored values (grads flow through it)
        self._vt = values_tensor if values_tensor is not None \
            else Tensor(bcoo.data)

    @property
    def shape(self):
        return list(self._shape)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self):
        return self._vt

    @property
    def dtype(self):
        return self._bcoo.data.dtype

    def nnz(self):
        return int(self._bcoo.nse)

    def to_dense(self):
        from ..core.dispatch import apply
        idx = self._bcoo.indices
        shape = tuple(self._shape)

        def scatter(v):
            dense = jnp.zeros(shape, v.dtype)
            return dense.at[tuple(idx.T)].add(v)

        return apply(scatter, self._vt, name="sparse_to_dense")

    def to_sparse_csr(self):
        coo = self._bcoo.sum_duplicates()
        idx = np.asarray(coo.indices)
        vals = np.asarray(coo.data)
        order = np.lexsort((idx[:, 1], idx[:, 0]))
        rows, cols = idx[order, 0], idx[order, 1]
        n_rows = self._shape[0]
        crows = np.zeros(n_rows + 1, np.int32)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows).astype(np.int32)
        return SparseCsrTensor(crows, cols.astype(np.int32), vals[order],
                               self._shape)

    def coalesce(self):
        """Sum duplicate coordinates; keeps the values' tape link (the
        duplicate reduction is a recorded segment_sum, and the no-dup case
        returns self unchanged)."""
        idx = np.asarray(jax.device_get(self._bcoo.indices))
        uniq, inv = np.unique(idx, axis=0, return_inverse=True)
        if uniq.shape[0] == idx.shape[0]:
            return self
        from ..core.dispatch import apply
        seg = jnp.asarray(inv.reshape(-1), jnp.int32)
        n = uniq.shape[0]
        vt = apply(lambda v: jax.ops.segment_sum(v, seg, num_segments=n),
                   self._vt, name="sparse_coalesce")
        return _make_coo(vt, jnp.asarray(uniq, self._bcoo.indices.dtype),
                         self._shape)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self._shape}, "
                f"nnz={self.nnz()})")


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape, _values_tensor=None):
        self.crows_arr = jnp.asarray(unwrap(crows), jnp.int32)
        self.cols_arr = jnp.asarray(unwrap(cols), jnp.int32)
        self.values_arr = jnp.asarray(unwrap(values))
        self._vt = _values_tensor if _values_tensor is not None \
            else Tensor(self.values_arr)
        self._shape = list(shape)

    @property
    def shape(self):
        return list(self._shape)

    def crows(self):
        return Tensor(self.crows_arr)

    def cols(self):
        return Tensor(self.cols_arr)

    def values(self):
        return self._vt

    def nnz(self):
        return int(self.values_arr.shape[0])

    def to_dense(self):
        return self.to_sparse_coo().to_dense()

    def to_sparse_coo(self, sparse_dim=2):
        shape = tuple(self._shape)
        crows = np.asarray(jax.device_get(self.crows_arr)).reshape(-1)
        n_rows = shape[-2]
        if len(shape) == 2:
            counts = crows[1:] - crows[:-1]
            rows = np.repeat(np.arange(n_rows), counts)
            idx = np.stack([rows, np.asarray(
                jax.device_get(self.cols_arr))], axis=1)
        else:
            # batched CSR: crows is nbatch blocks of (rows+1) pointers
            nbatch = int(np.prod(shape[:-2]))
            if crows.shape[0] != nbatch * (n_rows + 1):
                raise ValueError(
                    f"batched CSR crows must have {nbatch}*({n_rows}+1) "
                    f"entries, got {crows.shape[0]}")
            rows_l, batch_l = [], []
            for b in range(nbatch):
                seg = crows[b * (n_rows + 1):(b + 1) * (n_rows + 1)]
                cnt = seg[1:] - seg[:-1]
                rows_l.append(np.repeat(np.arange(n_rows), cnt))
                batch_l.append(np.full(int(seg[-1] - seg[0]), b, np.int64))
            rows = np.concatenate(rows_l)
            batches = np.concatenate(batch_l)
            bcols = []
            rem = batches.copy()
            for dim in reversed(shape[:-2]):
                bcols.append(rem % dim)
                rem //= dim
            idx = np.stack([*reversed(bcols), rows, np.asarray(
                jax.device_get(self.cols_arr)).reshape(-1)], axis=1)
        if idx.shape[0] != self.nnz():
            raise ValueError("CSR crows/cols disagree on nnz")
        bcoo = jsparse.BCOO((self.values_arr,
                             jnp.asarray(idx, jnp.int32)), shape=shape)
        return SparseCooTensor(bcoo, values_tensor=self._vt)

    def __repr__(self):
        return f"SparseCsrTensor(shape={self._shape}, nnz={self.nnz()})"


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    idx = jnp.asarray(unwrap(indices), jnp.int32)
    vt = values if isinstance(values, Tensor) else None
    vals = jnp.asarray(unwrap(values))
    if dtype is not None:
        from ..core import dtype as dtype_mod
        target = dtype_mod.convert_dtype(dtype)
        if vals.dtype != target:
            if vt is not None:
                from .. import ops
                vt = ops.cast(vt, dtype)
                vals = vt._data
            else:
                vals = vals.astype(target)
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    bcoo = jsparse.BCOO((vals, jnp.swapaxes(idx, 0, 1)),
                        shape=tuple(shape))
    return SparseCooTensor(bcoo, shape, values_tensor=vt)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    vt = values if isinstance(values, Tensor) else None
    return SparseCsrTensor(crows, cols, values, shape, _values_tensor=vt)


def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


def _make_coo(values_tensor, indices, shape):
    """Build a SparseCooTensor whose values keep their tape link."""
    bcoo = jsparse.BCOO((values_tensor._data, indices), shape=tuple(shape))
    return SparseCooTensor(bcoo, list(shape), values_tensor=values_tensor)


def to_dense(x):
    return x.to_dense() if isinstance(
        x, (SparseCooTensor, SparseCsrTensor)) else x


def add(x, y):
    x, y = _coo(x), _coo(y)
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        data = jnp.concatenate([x._bcoo.data, y._bcoo.data])
        idx = jnp.concatenate([x._bcoo.indices, y._bcoo.indices])
        return SparseCooTensor(
            jsparse.BCOO((data, idx), shape=tuple(x._shape))
            .sum_duplicates(), x._shape)
    return Tensor(to_dense(x)._data + to_dense(y)._data)


def matmul(x, y):
    """sparse @ dense (reference paddle.sparse.matmul)."""
    from ..core.dispatch import apply
    x = _coo(x)
    if isinstance(x, SparseCooTensor):
        idx, shape = x._bcoo.indices, tuple(x._shape)
        yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(unwrap(y)))
        return apply(
            lambda v, ya: jsparse.BCOO((v, idx), shape=shape) @ ya,
            x.values(), yt, name="sparse_matmul")
    return Tensor(unwrap(x) @ unwrap(y))


def mv(x, vec):
    return matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    """beta * input + alpha * (x @ y) (reference
    python/paddle/sparse/multiary.py:29). Two layouts, like the
    reference: dense input + sparse x + dense y -> dense; all-sparse
    (COO or CSR) -> sparse of the same format."""
    from .. import ops
    from ..core.dispatch import apply
    if not isinstance(input, (SparseCooTensor, SparseCsrTensor)):
        prod = matmul(x, y)
        inp = input if isinstance(input, Tensor) else Tensor(
            jnp.asarray(unwrap(input)))
        return ops.add(ops.scale(inp, beta), ops.scale(prod, alpha))

    want_csr = isinstance(input, SparseCsrTensor)
    ic, xc, yc = _coo(input).coalesce(), _coo(x).coalesce(), \
        _coo(y).coalesce()
    shape = tuple(ic._shape)
    ndb = len(shape) - 2  # leading batch dims (2D or batched 3D)
    i_idx = np.asarray(jax.device_get(ic._bcoo.indices))
    x_idx = np.asarray(jax.device_get(xc._bcoo.indices))
    y_idx = np.asarray(jax.device_get(yc._bcoo.indices))

    # structural sparse-sparse matmul: join x's contraction column with
    # y's row, per batch — O(pairs), never densified. (The reference's
    # addmm_coo_coo kernel is the cuSPARSE SpGEMM analogue.)
    def lin(a, cols):
        out = np.zeros(a.shape[0], np.int64)
        for c in range(cols.shape[0]):
            out = out * cols[c] + a[:, c]
        return out

    dims_k = np.array([*shape[:ndb], xc._shape[-1]], np.int64)
    xk = lin(np.concatenate([x_idx[:, :ndb], x_idx[:, -1:]], axis=1),
             dims_k)
    yk = lin(np.concatenate([y_idx[:, :ndb], y_idx[:, -2:-1]], axis=1),
             dims_k)
    order_y = np.argsort(yk, kind="stable")
    yk_sorted = yk[order_y]
    lo = np.searchsorted(yk_sorted, xk, side="left")
    hi = np.searchsorted(yk_sorted, xk, side="right")
    reps = (hi - lo).astype(np.int64)
    xi = np.repeat(np.arange(x_idx.shape[0]), reps)
    within = np.arange(reps.sum()) - np.repeat(np.cumsum(reps) - reps,
                                               reps)
    yi = order_y[np.repeat(lo, reps) + within]
    prod_coords = np.concatenate(
        [x_idx[xi, :ndb], x_idx[xi, -2:-1], y_idx[yi, -1:]], axis=1)

    # output pattern = union of input's pattern and the product pattern
    dims_out = np.array(shape, np.int64)
    lin_prod = lin(prod_coords, dims_out)
    lin_in = lin(i_idx, dims_out)
    uniq = np.unique(np.concatenate([lin_prod, lin_in]))
    seg_prod = jnp.asarray(np.searchsorted(uniq, lin_prod), jnp.int32)
    seg_in = jnp.asarray(np.searchsorted(uniq, lin_in), jnp.int32)
    n_out = uniq.shape[0]
    out_coords = np.empty((n_out, len(shape)), np.int64)
    rem = uniq.copy()
    for c in range(len(shape) - 1, -1, -1):
        out_coords[:, c] = rem % dims_out[c]
        rem //= dims_out[c]
    xi_j, yi_j = jnp.asarray(xi, jnp.int32), jnp.asarray(yi, jnp.int32)

    def fwd(iv, xv, yv):
        contrib = jnp.take(xv, xi_j) * jnp.take(yv, yi_j)
        out = alpha * jax.ops.segment_sum(contrib, seg_prod,
                                          num_segments=n_out)
        return out.astype(iv.dtype).at[seg_in].add(beta * iv)

    vt = apply(fwd, ic._vt, xc._vt, yc._vt, name="sparse_addmm")
    idx = jnp.asarray(out_coords, jnp.int32)
    if not want_csr:
        return _make_coo(vt, idx, list(shape))
    nbatch = int(np.prod(shape[:ndb], dtype=np.int64)) if ndb else 1
    counts = np.zeros(nbatch * (shape[-2] + 1), np.int64)
    brow = (lin(out_coords[:, :ndb], dims_out[:ndb]) if ndb
            else np.zeros(n_out, np.int64))
    np.add.at(counts, brow * (shape[-2] + 1) + out_coords[:, -2] + 1, 1)
    crows = counts.reshape(nbatch, shape[-2] + 1).cumsum(axis=1).reshape(-1)
    return SparseCsrTensor(crows.astype(np.int32),
                           out_coords[:, -1].astype(np.int32), vt._data,
                           list(shape), _values_tensor=vt)


def masked_matmul(x, y, mask):
    """dense @ dense evaluated only at mask's sparsity pattern."""
    from ..core.dispatch import apply
    m = _coo(mask)
    idx = m._bcoo.indices
    xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(unwrap(x)))
    yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(unwrap(y)))
    vt = apply(lambda xa, ya: (xa @ ya)[idx[:, 0], idx[:, 1]], xt, yt,
               name="sparse_masked_matmul")
    return _make_coo(vt, idx, m._shape)


def relu(x):
    from .nn import functional as _F
    return _F.relu(x)


def transpose(x, perm):
    x = _coo(x)
    idx = x._bcoo.indices[:, jnp.asarray(perm)]
    shape = [x._shape[p] for p in perm]
    return SparseCooTensor(jsparse.BCOO((x._bcoo.data, idx),
                                        shape=tuple(shape)), shape)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


# ---------------------------------------------------------------------------
# elementwise value ops (reference python/paddle/sparse/unary.py /
# binary.py: each applies to the stored values, preserving sparsity)
# ---------------------------------------------------------------------------

def _unary_valueop(fn, name):
    def op(x, *args, **kwargs):
        from ..core.dispatch import apply
        c = _coo(x)
        vt = apply(lambda v: fn(v, *args, **kwargs), c.values(),
                   name=f"sparse_{name}")
        return _make_coo(vt, c._bcoo.indices, c._shape)
    op.__name__ = name
    return op


sin = _unary_valueop(jnp.sin, "sin")
sinh = _unary_valueop(jnp.sinh, "sinh")
asin = _unary_valueop(jnp.arcsin, "asin")
asinh = _unary_valueop(jnp.arcsinh, "asinh")
tan = _unary_valueop(jnp.tan, "tan")
tanh = _unary_valueop(jnp.tanh, "tanh")
atan = _unary_valueop(jnp.arctan, "atan")
atanh = _unary_valueop(jnp.arctanh, "atanh")
sqrt = _unary_valueop(jnp.sqrt, "sqrt")
square = _unary_valueop(jnp.square, "square")
log1p = _unary_valueop(jnp.log1p, "log1p")
expm1 = _unary_valueop(jnp.expm1, "expm1")
abs = _unary_valueop(jnp.abs, "abs")  # noqa: A001
neg = _unary_valueop(jnp.negative, "neg")
deg2rad = _unary_valueop(jnp.deg2rad, "deg2rad")
rad2deg = _unary_valueop(jnp.rad2deg, "rad2deg")
isnan = _unary_valueop(jnp.isnan, "isnan")


def pow(x, factor):  # noqa: A001
    return _unary_valueop(lambda v: jnp.power(v, factor), "pow")(x)


def cast(x, index_dtype=None, value_dtype=None):
    c = _coo(x)
    data = c._bcoo.data if value_dtype is None else \
        c._bcoo.data.astype(value_dtype)
    idx = c._bcoo.indices if index_dtype is None else \
        c._bcoo.indices.astype(index_dtype)
    return SparseCooTensor(jsparse.BCOO((data, idx),
                                        shape=tuple(c._shape)), c._shape)


def coalesce(x):
    return _coo(x).coalesce()


def _binary_valueop(fn, name):
    def op(x, y):
        a = _coo(x).coalesce()
        b = _coo(y).coalesce()
        # dense-side combine keeps semantics exact for mismatched patterns
        dense = fn(a._bcoo.todense(), b._bcoo.todense())
        return SparseCooTensor(jsparse.BCOO.fromdense(dense),
                               list(dense.shape))
    op.__name__ = name
    return op


subtract = _binary_valueop(jnp.subtract, "subtract")
multiply = _binary_valueop(jnp.multiply, "multiply")
divide = _binary_valueop(jnp.divide, "divide")


def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    c = _coo(x)
    out = jnp.sum(c._bcoo.todense(), axis=axis, dtype=dtype,
                  keepdims=keepdim)
    return Tensor(out)


def reshape(x, shape):
    c = _coo(x).coalesce()
    dense = c._bcoo.todense().reshape(shape)
    return SparseCooTensor(jsparse.BCOO.fromdense(dense),
                           list(dense.shape))


def slice(x, axes, starts, ends):  # noqa: A001
    c = _coo(x).coalesce()
    dense = c._bcoo.todense()
    import builtins
    idx = [builtins.slice(None)] * dense.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = builtins.slice(int(st), int(en))
    out = dense[tuple(idx)]
    return SparseCooTensor(jsparse.BCOO.fromdense(out), list(out.shape))


def mask_as(x, mask):
    """Keep x's dense values at mask's sparsity pattern (reference
    sparse/multiary.py mask_as)."""
    from ..core.dispatch import apply
    m = _coo(mask).coalesce()
    idx = m._bcoo.indices
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        xd = to_dense(x)
    else:
        xd = x if isinstance(x, Tensor) else Tensor(jnp.asarray(unwrap(x)))
    vt = apply(lambda d: d[tuple(idx.T)], xd, name="sparse_mask_as")
    return _make_coo(vt, idx, m._shape)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """PCA on a sparse matrix (reference paddle.sparse.pca_lowrank):
    densify + the shared lowrank path."""
    from ..ops.special import pca_lowrank as _dense_pca
    dense = Tensor(_coo(x)._bcoo.todense()) \
        if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x
    return _dense_pca(dense, q=q, center=center, niter=niter)


from . import nn  # noqa: E402  (layer/functional subpackage)
