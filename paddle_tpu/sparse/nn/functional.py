"""`paddle.sparse.nn.functional` — sparse conv / pooling / activations /
softmax / attention.

Reference surface: python/paddle/sparse/nn/functional/{conv,pooling,
activation,transformer}.py backed by the CUDA rulebook kernels
(paddle/phi/kernels/sparse/gpu/conv_kernel.cu, sparse attention via
fused CSR softmax kernels).

TPU-first design: the rulebook (which active input site feeds which
active output site, per kernel offset) is integer bookkeeping computed
once on host from the concrete COO coordinates; the device-side compute
is K dense gather->matmul->scatter-add steps, one (n_pairs_k, Cin) @
(Cin, Cout) GEMM per kernel offset — exactly the shape the MXU wants.
Gradients flow through the gathers/GEMMs via the eager tape (jax.vjp in
core/dispatch.apply); the rulebook itself is static data. Sparse ops are
eager-only (coordinates must be concrete to build the rulebook), which
matches how point-cloud pipelines use them.
"""

from __future__ import annotations

import itertools
import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply, unwrap
from ...core.tensor import Tensor

__all__ = [
    "conv2d", "conv3d", "subm_conv2d", "subm_conv2d_igemm", "subm_conv3d",
    "subm_conv3d_igemm", "max_pool3d", "relu", "relu6", "leaky_relu",
    "softmax", "attention",
]


# ---------------------------------------------------------------------------
# rulebook construction (host-side integer bookkeeping)
# ---------------------------------------------------------------------------

def _norm_tuple(v, n, name):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    v = tuple(int(e) for e in v)
    if len(v) != n:
        raise ValueError(f"{name} must be an int or length-{n}, got {v}")
    return v


def _norm_padding(padding, n):
    """Return (lo, hi) padding per spatial dim."""
    if isinstance(padding, str):
        raise ValueError(
            "string padding modes are not supported for sparse conv; "
            "pass explicit integer padding")
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n and all(
            isinstance(p, (int, np.integer)) for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1]))
                for i in range(n)]
    if len(padding) == n:  # list of (lo, hi) pairs
        return [(int(p[0]), int(p[1])) for p in padding]
    raise ValueError(f"bad padding {padding!r} for {n} spatial dims")


_RULEBOOK_CACHE = {}
_RULEBOOK_CACHE_MAX = 128


def _rulebook_cached(coords, user_key, geom, build):
    """Rulebook cache (the reference's `key` mechanism — conv_kernel.cu
    caches the rulebook per key in the op's context). With no user key the
    coordinate bytes themselves key the entry, so static point clouds
    (e.g. a fixed voxel grid trained for many steps) skip the host-side
    rebuild."""
    ck = (user_key, hash(coords.tobytes()), coords.shape[0], geom)
    hit = _RULEBOOK_CACHE.get(ck)
    if hit is not None:
        return hit
    out = build()
    if len(_RULEBOOK_CACHE) >= _RULEBOOK_CACHE_MAX:
        _RULEBOOK_CACHE.pop(next(iter(_RULEBOOK_CACHE)))
    _RULEBOOK_CACHE[ck] = out
    return out


def _conv_rulebook(coords, spatial_in, ksize, stride, padding, dilation):
    """Full (non-submanifold) sparse conv rulebook, vectorized in numpy.

    coords: (nnz, 1+nd) int array [batch, *spatial]. Returns
    (out_coords (n_out, 1+nd), out_spatial, pairs) where pairs[k] =
    (in_rows, out_rows) int arrays for kernel offset k.
    """
    nd = len(spatial_in)
    out_spatial = tuple(
        (spatial_in[i] + padding[i][0] + padding[i][1]
         - (dilation[i] * (ksize[i] - 1) + 1)) // stride[i] + 1
        for i in range(nd))
    offsets = list(itertools.product(*(range(k) for k in ksize)))
    coords = np.asarray(coords, np.int64)
    sp = coords[:, 1:]
    pad_lo = np.array([p[0] for p in padding], np.int64)
    dil = np.array(dilation, np.int64)
    strd = np.array(stride, np.int64)
    out_hi = np.array(out_spatial, np.int64)
    srcs_per, ocand_per = [], []
    for off in offsets:
        num = sp + pad_lo - np.array(off, np.int64) * dil
        q, r = np.divmod(num, strd)
        valid = ((r == 0) & (q >= 0) & (q < out_hi)).all(axis=1)
        src = np.nonzero(valid)[0]
        srcs_per.append(src)
        ocand_per.append(
            np.concatenate([coords[src, :1], q[src]], axis=1))
    counts = [s.shape[0] for s in srcs_per]
    if sum(counts) == 0:
        return (np.zeros((0, 1 + nd), np.int64), out_spatial,
                [(np.zeros(0, np.int32), np.zeros(0, np.int32))
                 for _ in offsets])
    all_cand = np.concatenate(ocand_per, axis=0)
    # linearize (batch, *out_spatial) so np.unique sorts lexicographically
    dims = (coords[:, 0].max() + 1, *out_spatial)
    lin = np.ravel_multi_index(tuple(all_cand.T), dims)
    uniq, inv = np.unique(lin, return_inverse=True)
    out_coords = np.stack(np.unravel_index(uniq, dims), axis=1)
    pairs = []
    pos = 0
    for src, cnt in zip(srcs_per, counts):
        pairs.append((src.astype(np.int32),
                      inv[pos:pos + cnt].astype(np.int32)))
        pos += cnt
    return out_coords, out_spatial, pairs


def _subm_rulebook(coords, spatial_in, ksize, dilation):
    """Submanifold rulebook, vectorized: output coords == input coords;
    offset k reads input at p + (k - center) * dilation when active.
    Active-site lookup = binary search over the linearized sorted
    coordinates."""
    nd = len(ksize)
    center = tuple(k // 2 for k in ksize)
    offsets = list(itertools.product(*(range(k) for k in ksize)))
    coords = np.asarray(coords, np.int64)
    if coords.shape[0] == 0:
        return [(np.zeros(0, np.int32), np.zeros(0, np.int32))
                for _ in offsets]
    dims = (coords[:, 0].max() + 1, *spatial_in)
    lin_in = np.ravel_multi_index(tuple(coords.T), dims)
    order = np.argsort(lin_in)
    sorted_lin = lin_in[order]
    hi = np.array(spatial_in, np.int64)
    pairs = []
    for off in offsets:
        delta = np.array([(off[i] - center[i]) * dilation[i]
                          for i in range(nd)], np.int64)
        tgt = coords[:, 1:] + delta
        valid = ((tgt >= 0) & (tgt < hi)).all(axis=1)
        rows = np.nonzero(valid)[0]
        tgt_full = np.concatenate([coords[rows, :1], tgt[rows]], axis=1)
        lin_t = np.ravel_multi_index(tuple(tgt_full.T), dims)
        pos = np.searchsorted(sorted_lin, lin_t)
        pos = np.minimum(pos, sorted_lin.shape[0] - 1)
        found = sorted_lin[pos] == lin_t
        pairs.append((order[pos[found]].astype(np.int32),
                      rows[found].astype(np.int32)))
    return pairs


def _gather_gemm_scatter(vals_t, weight, bias, pairs, n_out, ksize,
                         in_ch, out_ch, name):
    """K gather->GEMM->scatter-add steps through the autograd tape."""
    K = int(np.prod(ksize))
    idx_pairs = [(jnp.asarray(a), jnp.asarray(b)) for a, b in pairs]

    def fwd(vals, w, b_):
        wk = jnp.reshape(w, (K, in_ch, out_ch))
        out = jnp.zeros((n_out, out_ch), vals.dtype)
        for ki, (src, dst) in enumerate(idx_pairs):
            if src.shape[0] == 0:
                continue
            out = out.at[dst].add(
                jnp.take(vals, src, axis=0) @ wk[ki].astype(vals.dtype))
        if b_ is not None:
            out = out + b_.astype(vals.dtype)
        return out

    if bias is None:
        return apply(lambda v, w: fwd(v, w, None), vals_t, weight, name=name)
    return apply(fwd, vals_t, weight, bias, name=name)


def _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                 subm, nd, name, key=None):
    from .. import SparseCooTensor, _make_coo, _coo
    if groups != 1:
        raise ValueError("sparse conv supports groups=1 only "
                         "(matching the reference)")
    x = _coo(x)
    shape = list(x.shape)
    if len(shape) != nd + 2:
        raise ValueError(
            f"sparse conv{nd}d input must be [N, *spatial, C], got {shape}")
    spatial_in = tuple(shape[1:-1])
    stride = _norm_tuple(stride, nd, "stride")
    dilation = _norm_tuple(dilation, nd, "dilation")
    padding = _norm_padding(padding, nd)
    w = unwrap(weight) if not isinstance(weight, Tensor) else weight._data
    ksize = tuple(int(s) for s in w.shape[:nd])
    in_ch, out_ch = int(w.shape[nd]), int(w.shape[nd + 1])
    if in_ch != shape[-1]:
        raise ValueError(f"weight in_channels {in_ch} != input C {shape[-1]}")

    coords = np.asarray(jax.device_get(x._bcoo.indices))
    vals_t = x.values()
    wt = weight if isinstance(weight, Tensor) else Tensor(jnp.asarray(w))
    bt = None
    if bias is not None:
        bt = bias if isinstance(bias, Tensor) else Tensor(
            jnp.asarray(unwrap(bias)))

    geom = (subm, spatial_in, ksize, stride, tuple(padding), dilation)
    if subm:
        if any(s != 1 for s in stride):
            raise ValueError("submanifold conv requires stride 1")
        pairs = _rulebook_cached(
            coords, key, geom,
            lambda: _subm_rulebook(coords, spatial_in, ksize, dilation))
        out_coords, out_spatial = coords, spatial_in
    else:
        out_coords, out_spatial, pairs = _rulebook_cached(
            coords, key, geom,
            lambda: _conv_rulebook(coords, spatial_in, ksize, stride,
                                   padding, dilation))
    out_shape = [shape[0], *out_spatial, out_ch]
    vt = _gather_gemm_scatter(vals_t, wt, bt, pairs, out_coords.shape[0],
                              ksize, in_ch, out_ch, name)
    return _make_coo(vt, jnp.asarray(out_coords, jnp.int32), out_shape)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Sparse 3D convolution over a [N, D, H, W, C] SparseCooTensor
    (reference python/paddle/sparse/nn/functional/conv.py:380)."""
    assert data_format == "NDHWC", data_format
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        subm=False, nd=3, name="sparse_conv3d")


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold sparse 3D conv: output sparsity pattern == input's
    (reference conv.py:486). `key` names the cached rulebook."""
    assert data_format == "NDHWC", data_format
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        subm=True, nd=3, name="sparse_subm_conv3d", key=key)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    """Sparse 2D convolution over a [N, H, W, C] SparseCooTensor
    (reference conv.py:710)."""
    assert data_format == "NHWC", data_format
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        subm=False, nd=2, name="sparse_conv2d")


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    """Submanifold sparse 2D conv (reference conv.py:814)."""
    assert data_format == "NHWC", data_format
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        subm=True, nd=2, name="sparse_subm_conv2d", key=key)


def subm_conv3d_igemm(x, weight, bias=None, stride=1, padding=0, dilation=1,
                      groups=1, data_format="NDHWC", key=None, name=None):
    """Implicit-GEMM backend alias (reference conv.py:598). Our engine IS
    gather-GEMM-scatter, so this is the same path."""
    return subm_conv3d(x, weight, bias, stride, padding, dilation, groups,
                       data_format, key, name)


def subm_conv2d_igemm(x, weight, bias=None, stride=1, padding=0, dilation=1,
                      groups=1, data_format="NHWC", key=None, name=None):
    """Implicit-GEMM backend alias (reference conv.py:923)."""
    return subm_conv2d(x, weight, bias, stride, padding, dilation, groups,
                       data_format, key, name)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """Sparse 3D max pooling over active sites (reference
    python/paddle/sparse/nn/functional/pooling.py; CUDA kernel
    paddle/phi/kernels/sparse/gpu/pool_kernel.cu)."""
    from .. import _make_coo, _coo
    assert data_format == "NDHWC", data_format
    assert not ceil_mode, "ceil_mode not supported for sparse max_pool3d"
    x = _coo(x)
    shape = list(x.shape)
    nd = 3
    spatial_in = tuple(shape[1:-1])
    ksize = _norm_tuple(kernel_size, nd, "kernel_size")
    stride = _norm_tuple(stride if stride is not None else kernel_size,
                         nd, "stride")
    padding = _norm_padding(padding, nd)
    dilation = (1,) * nd
    coords = np.asarray(jax.device_get(x._bcoo.indices))
    out_coords, out_spatial, pairs = _rulebook_cached(
        coords, None, ("pool", spatial_in, ksize, stride, tuple(padding)),
        lambda: _conv_rulebook(coords, spatial_in, ksize, stride, padding,
                               dilation))
    n_out = out_coords.shape[0]
    C = shape[-1]
    idx_pairs = [(jnp.asarray(a), jnp.asarray(b)) for a, b in pairs]

    def fwd(vals):
        neg = jnp.asarray(-jnp.inf, vals.dtype)
        out = jnp.full((n_out, C), neg, vals.dtype)
        for src, dst in idx_pairs:
            if src.shape[0] == 0:
                continue
            out = out.at[dst].max(jnp.take(vals, src, axis=0))
        return out

    vt = apply(fwd, x.values(), name="sparse_max_pool3d")
    out_shape = [shape[0], *out_spatial, C]
    return _make_coo(vt, jnp.asarray(out_coords, jnp.int32), out_shape)


# ---------------------------------------------------------------------------
# activations / softmax
# ---------------------------------------------------------------------------

def _valueop(x, fn, name):
    from .. import _make_coo, _coo
    c = _coo(x)
    vt = apply(fn, c.values(), name=name)
    return _make_coo(vt, c._bcoo.indices, c.shape)


def relu(x, name=None):
    return _valueop(x, lambda v: jnp.maximum(v, 0), "sparse_relu")


def relu6(x, name=None):
    return _valueop(x, lambda v: jnp.clip(v, 0, 6), "sparse_relu6")


def leaky_relu(x, negative_slope=0.01, name=None):
    return _valueop(
        x, lambda v: jnp.where(v >= 0, v, v * negative_slope),
        "sparse_leaky_relu")


def _segment_softmax(vals, seg_ids, n_seg):
    """Numerically-stable softmax within each segment. Guards both empty
    segments and all--inf segments (a fully key-padded attention row):
    a non-finite segment max is replaced by 0 so exp(-inf - 0) = 0, and
    zero denominators yield 0, not NaN."""
    m = jax.ops.segment_max(vals, seg_ids, num_segments=n_seg)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(vals - m[seg_ids])
    denom = jax.ops.segment_sum(p, seg_ids, num_segments=n_seg)
    return p / jnp.where(denom == 0, 1.0, denom)[seg_ids]


def softmax(x, axis=-1, name=None):
    """Softmax over the stored values of each last-dim row, treating
    absent entries as -inf (reference sparse/nn/functional/activation.py;
    CUDA kernel paddle/phi/kernels/sparse/gpu/softmax_kernel.cu).

    Supports axis=-1 on 2D/3D COO and CSR tensors.
    """
    from .. import SparseCsrTensor, SparseCooTensor, _make_coo
    if axis != -1:
        raise ValueError("sparse softmax supports axis=-1 only")
    if isinstance(x, SparseCsrTensor):
        crows = np.asarray(jax.device_get(x.crows_arr)).reshape(-1)
        shape = list(x.shape)
        s_rows = shape[-2]
        nbatch = int(np.prod(shape[:-2])) if len(shape) > 2 else 1
        counts = []
        for b in range(nbatch):
            seg = crows[b * (s_rows + 1):(b + 1) * (s_rows + 1)]
            counts.extend((seg[1:] - seg[:-1]).tolist())
        rows = np.repeat(np.arange(len(counts)), counts)
        seg_ids = jnp.asarray(rows, jnp.int32)
        n_seg = len(counts)
        vt = apply(lambda v: _segment_softmax(v, seg_ids, n_seg),
                   x.values(), name="sparse_softmax")
        return SparseCsrTensor(x.crows_arr, x.cols_arr, vt._data, shape,
                               _values_tensor=vt)
    c = x.coalesce() if isinstance(x, SparseCooTensor) else x
    idx = np.asarray(jax.device_get(c._bcoo.indices))
    # group by all coords except the last sparse dim
    uniq, rows = np.unique(idx[:, :-1], axis=0, return_inverse=True)
    seg_ids = jnp.asarray(rows.reshape(-1), jnp.int32)
    n_seg = uniq.shape[0]
    vt = apply(lambda v: _segment_softmax(v, seg_ids, n_seg),
               c.values(), name="sparse_softmax")
    return _make_coo(vt, c._bcoo.indices, c.shape)


# ---------------------------------------------------------------------------
# sparse attention
# ---------------------------------------------------------------------------

def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """softmax(QK^T/sqrt(d) + masks) @ V evaluated only at sparse_mask's
    CSR sparsity pattern (reference
    python/paddle/sparse/nn/functional/transformer.py:29; CUDA kernel
    paddle/phi/kernels/sparse/gpu/fused_attention_kernel.cu).

    query/key/value: [batch, num_heads, seq_len, head_dim] dense;
    sparse_mask: SparseCsrTensor with dense shape [batch*num_heads,
    seq_len, seq_len]. Returns a dense [batch, num_heads, seq, dim]
    Tensor. The per-entry score gather, segment softmax and weighted
    segment-sum all ride XLA gather/scatter; gradients flow to q/k/v.
    """
    q = query if isinstance(query, Tensor) else Tensor(jnp.asarray(query))
    k = key if isinstance(key, Tensor) else Tensor(jnp.asarray(key))
    v = value if isinstance(value, Tensor) else Tensor(jnp.asarray(value))
    b, h, s, d = q.shape
    bh = b * h
    crows = np.asarray(jax.device_get(sparse_mask.crows_arr)).reshape(-1)
    cols = np.asarray(jax.device_get(sparse_mask.cols_arr)).reshape(-1)
    if crows.shape[0] != bh * (s + 1):
        raise ValueError(
            f"sparse_mask crows must cover [batch*num_heads, seq] = "
            f"[{bh}, {s}], got {crows.shape[0]} row pointers")
    rows_l = []
    batch_l = []
    for i in range(bh):
        seg = crows[i * (s + 1):(i + 1) * (s + 1)]
        counts = seg[1:] - seg[:-1]
        rows_l.append(np.repeat(np.arange(s), counts))
        batch_l.append(np.full(int(seg[-1] - seg[0]), i, np.int64))
    rows = np.concatenate(rows_l)
    batches = np.concatenate(batch_l)
    if rows.shape[0] != cols.shape[0]:
        raise ValueError("sparse_mask crows/cols disagree on nnz")
    seg_global = jnp.asarray(batches * s + rows, jnp.int32)
    rows_j = jnp.asarray(rows, jnp.int32)
    cols_j = jnp.asarray(cols, jnp.int32)
    batches_j = jnp.asarray(batches, jnp.int32)
    n_seg = bh * s
    scale = 1.0 / math.sqrt(d)

    kp = None if key_padding_mask is None else unwrap(key_padding_mask)
    am = None if attn_mask is None else unwrap(attn_mask)

    def fwd(qa, ka, va):
        qf = qa.reshape(bh, s, d)
        kf = ka.reshape(bh, s, d)
        vf = va.reshape(bh, s, d)
        qg = qf[batches_j, rows_j]          # (nnz, d)
        kg = kf[batches_j, cols_j]
        score = jnp.sum(qg * kg, axis=-1) * scale
        if kp is not None:
            kp_b = jnp.asarray(kp)[batches_j // h, cols_j]
            score = score + kp_b.astype(score.dtype)
        if am is not None:
            score = score + jnp.asarray(am)[rows_j, cols_j].astype(
                score.dtype)
        attn = _segment_softmax(score, seg_global, n_seg)
        vg = vf[batches_j, cols_j]
        out = jax.ops.segment_sum(attn[:, None] * vg, seg_global,
                                  num_segments=n_seg)
        return out.reshape(b, h, s, d)

    return apply(fwd, q, k, v, name="sparse_attention")
