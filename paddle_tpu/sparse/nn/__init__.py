"""`paddle.sparse.nn` — layer wrappers over the sparse functionals.

Reference surface: python/paddle/sparse/nn/__init__.py (ReLU, ReLU6,
LeakyReLU, Softmax, BatchNorm, SyncBatchNorm, Conv2D, Conv3D, SubmConv2D,
SubmConv3D, MaxPool3D) with layer definitions in sparse/nn/layer/.
"""

from __future__ import annotations

import numpy as np

from ... import nn as dense_nn
from . import functional  # noqa: F401
from . import functional as F

__all__ = [
    "ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm", "SyncBatchNorm",
    "Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D", "MaxPool3D",
]


class ReLU(dense_nn.Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(dense_nn.Layer):
    def forward(self, x):
        return F.relu6(x)


class LeakyReLU(dense_nn.Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class Softmax(dense_nn.Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class _Conv(dense_nn.Layer):
    """Shared sparse-conv layer body (reference
    python/paddle/sparse/nn/layer/conv.py:46)."""

    def __init__(self, nd, in_channels, out_channels, kernel_size, stride,
                 padding, dilation, groups, subm, key, padding_mode,
                 weight_attr, bias_attr, data_format, backend):
        super().__init__()
        assert padding_mode == "zeros", padding_mode
        assert backend in (None, "igemm"), backend
        self._nd = nd
        self._subm = subm
        self._key = key
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = F._norm_tuple(kernel_size, nd, "kernel_size")
        self.stride = F._norm_tuple(stride, nd, "stride")
        self.padding = padding
        self.dilation = F._norm_tuple(dilation, nd, "dilation")
        self.groups = groups
        self.data_format = data_format
        fan_in = in_channels * int(np.prod(self.kernel_size))
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            shape=[*self.kernel_size, in_channels // groups, out_channels],
            attr=weight_attr,
            default_initializer=dense_nn.initializer.Uniform(-bound, bound))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=[out_channels], attr=bias_attr, is_bias=True,
                default_initializer=dense_nn.initializer.Uniform(
                    -bound, bound))

    def forward(self, x):
        fn = {
            (2, False): F.conv2d, (2, True): F.subm_conv2d,
            (3, False): F.conv3d, (3, True): F.subm_conv3d,
        }[(self._nd, self._subm)]
        kwargs = {} if not self._subm else {"key": self._key}
        return fn(x, self.weight, self.bias, stride=self.stride,
                  padding=self.padding, dilation=self.dilation,
                  groups=self.groups, data_format=self.data_format,
                  **kwargs)

    def extra_repr(self):
        s = (f"{self.in_channels}, {self.out_channels}, "
             f"kernel_size={self.kernel_size}, stride={self.stride}")
        if self._subm:
            s += ", subm=True"
        return s


class Conv3D(_Conv):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 backend=None):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, False, None,
                         padding_mode, weight_attr, bias_attr, data_format,
                         backend)


class SubmConv3D(_Conv):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, key=None,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NDHWC", backend=None):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, True, key, padding_mode,
                         weight_attr, bias_attr, data_format, backend)


class Conv2D(_Conv):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC",
                 backend=None):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, False, None,
                         padding_mode, weight_attr, bias_attr, data_format,
                         backend)


class SubmConv2D(_Conv):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, key=None,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NHWC", backend=None):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, True, key, padding_mode,
                         weight_attr, bias_attr, data_format, backend)


class MaxPool3D(dense_nn.Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NDHWC", name=None):
        super().__init__()
        assert not return_mask, "return_mask not supported"
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.data_format)


class BatchNorm(dense_nn.BatchNorm1D):
    """BatchNorm over the active values of a SparseCooTensor (reference
    python/paddle/sparse/nn/layer/norm.py:35 — subclasses the dense
    BatchNorm1D and applies it to the (nnz, C) values)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum=momentum, epsilon=epsilon,
                         weight_attr=weight_attr, bias_attr=bias_attr,
                         data_format="NC",
                         use_global_stats=use_global_stats, name=name)
        self._sparse_data_format = data_format

    def forward(self, x):
        from .. import _make_coo, _coo
        c = _coo(x)
        vt = super().forward(c.values())
        return _make_coo(vt, c._bcoo.indices, c.shape)


class SyncBatchNorm(BatchNorm):
    """Cross-replica BatchNorm (reference sparse/nn/layer/norm.py
    SyncBatchNorm). Under SPMD the batch statistics of the compiled step
    are already global (GSPMD inserts the cross-replica reduction for the
    mean/var reductions); eager single-process semantics equal BatchNorm.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, BatchNorm) and not isinstance(
                layer, SyncBatchNorm):
            out = SyncBatchNorm(
                layer._num_features, momentum=layer._momentum,
                epsilon=layer._epsilon,
                data_format=layer._sparse_data_format,
                use_global_stats=layer._use_global_stats)
            if layer.weight is not None:
                out.weight.set_value(layer.weight)
            if layer.bias is not None:
                out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in layer.named_children():
            new = cls.convert_sync_batchnorm(sub)
            if new is not sub:
                setattr(out, name, new)
        return out
