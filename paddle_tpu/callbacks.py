"""`paddle.callbacks` (reference python/paddle/callbacks.py re-exports
the hapi training callbacks)."""

from .hapi.callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
    ReduceLROnPlateau,
    VisualDL,
    WandbCallback,
)

__all__ = [
    "Callback",
    "ProgBarLogger",
    "ModelCheckpoint",
    "VisualDL",
    "LRScheduler",
    "EarlyStopping",
    "ReduceLROnPlateau",
    "WandbCallback",
]
