"""`paddle.reader` — functional reader-decorator utilities (parity:
reference python/paddle/reader/decorator.py: cache, map_readers,
shuffle, chain, compose, buffered, firstn, xmap_readers,
multiprocess_reader). A *reader* is a zero-arg callable returning an
iterable of samples; decorators wrap readers into new readers."""

from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = []  # reference keeps these importable but un-exported


class _Raise:
    """Exception carrier: a worker thread that dies must surface its
    error at the consumer, never leave it blocked on q.get()."""

    def __init__(self, exc):
        self.exc = exc


def cache(reader):
    """Materialize the wrapped reader once; replay from memory after."""
    memo = []
    filled = [False]

    def wrapped():
        if not filled[0]:
            memo.extend(reader())
            filled[0] = True
        return iter(memo)
    return wrapped


def map_readers(func, *readers):
    """Yield ``func(a, b, ...)`` over the zipped sample streams."""
    def wrapped():
        its = [r() for r in readers]
        for args in zip(*its):
            yield func(*args)
    return wrapped


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer of ``buf_size`` samples."""
    def wrapped():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return wrapped


def chain(*readers):
    """Concatenate sample streams end to end."""
    def wrapped():
        return itertools.chain(*(r() for r in readers))
    return wrapped


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, check_alignment=True):
    """Zip readers into flat tuples: samples (1, 2) + (3, 4) -> (1, 2,
    3, 4); raises ComposeNotAligned when streams end unevenly (unless
    ``check_alignment`` is False)."""
    def _tuple(s):
        return s if isinstance(s, tuple) else (s,)

    def wrapped():
        its = [r() for r in readers]
        _SENTINEL = object()
        while True:
            row = [next(it, _SENTINEL) for it in its]
            done = [s is _SENTINEL for s in row]
            if all(done):
                return
            if any(done):
                if check_alignment:
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                return
            yield sum((_tuple(s) for s in row), ())
    return wrapped


def buffered(reader, size):
    """Prefetch up to ``size`` samples on a background thread."""
    def wrapped():
        q = _queue.Queue(maxsize=size)
        _END = object()

        def fill():
            try:
                for s in reader():
                    q.put(s)
                q.put(_END)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                q.put(_Raise(e))

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is _END:
                return
            if isinstance(s, _Raise):
                raise s.exc
            yield s
    return wrapped


def firstn(reader, n):
    """Only the first ``n`` samples."""
    def wrapped():
        return itertools.islice(reader(), n)
    return wrapped


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Map samples on ``process_num`` worker threads, optionally
    preserving input order."""
    def wrapped():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)
        _END = object()

        def feed():
            try:
                for i, s in enumerate(reader()):
                    in_q.put((i, s))
                for _ in range(process_num):
                    in_q.put(_END)
            except BaseException as e:  # noqa: BLE001
                out_q.put(_Raise(e))

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is _END:
                        out_q.put(_END)
                        return
                    i, s = item
                    out_q.put((i, mapper(s)))
            except BaseException as e:  # noqa: BLE001
                out_q.put(_Raise(e))

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()
        done = 0
        if not order:
            while done < process_num:
                item = out_q.get()
                if item is _END:
                    done += 1
                    continue
                if isinstance(item, _Raise):
                    raise item.exc
                yield item[1]
            return
        pending = {}
        want = 0
        while done < process_num or pending:
            if want in pending:
                yield pending.pop(want)
                want += 1
                continue
            item = out_q.get()
            if item is _END:
                done += 1
                continue
            if isinstance(item, _Raise):
                raise item.exc
            i, mapped = item
            pending[i] = mapped
    return wrapped


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave multiple readers concurrently (thread-backed here:
    samples are numpy on a single host; the reference uses processes
    to dodge the GIL its C++ readers don't hold)."""
    def wrapped():
        q = _queue.Queue(queue_size)
        _END = object()

        def fill(r):
            try:
                for s in r():
                    q.put(s)
                q.put(_END)
            except BaseException as e:  # noqa: BLE001
                q.put(_Raise(e))

        for r in readers:
            threading.Thread(target=fill, args=(r,), daemon=True).start()
        done = 0
        while done < len(readers):
            s = q.get()
            if s is _END:
                done += 1
                continue
            if isinstance(s, _Raise):
                raise s.exc
            yield s
    return wrapped
