"""`paddle.signal` (reference: python/paddle/signal.py — stft/istft)."""

from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import apply, unwrap

__all__ = ["stft", "istft", "frame", "overlap_add"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    def fn(a):
        n = (a.shape[axis] - frame_length) // hop_length + 1
        starts = jnp.arange(n) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        moved = jnp.moveaxis(a, axis, -1)
        frames = moved[..., idx]  # [..., n, frame_length]
        frames = jnp.swapaxes(frames, -1, -2)  # [..., frame_length, n]
        return frames if axis in (-1, a.ndim - 1) else \
            jnp.moveaxis(frames, (-2, -1), (axis, axis + 1))
    return apply(fn, x, name="frame")


def overlap_add(x, hop_length, axis=-1, name=None):
    def fn(a):
        # a: [..., frame_length, n_frames]
        fl, n = a.shape[-2], a.shape[-1]
        out_len = (n - 1) * hop_length + fl
        out = jnp.zeros(a.shape[:-2] + (out_len,), a.dtype)
        for i in range(n):  # static unroll (n known at trace time)
            out = out.at[..., i * hop_length:i * hop_length + fl].add(
                a[..., i])
        return out
    return apply(fn, x, name="overlap_add")


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win = unwrap(window) if window is not None else jnp.ones(win_length)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))

    def fn(a):
        squeeze = a.ndim == 1
        if squeeze:
            a = a[None]
        if center:
            a = jnp.pad(a, ((0, 0), (n_fft // 2, n_fft // 2)),
                        mode=pad_mode)
        n_frames = 1 + (a.shape[-1] - n_fft) // hop_length
        idx = (jnp.arange(n_frames)[:, None] * hop_length +
               jnp.arange(n_fft)[None, :])
        frames = a[:, idx] * win
        spec = jnp.fft.rfft(frames, axis=-1) if onesided else \
            jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.float32(n_fft))
        out = jnp.swapaxes(spec, 1, 2)  # [b, freq, frames]
        return out[0] if squeeze else out

    return apply(fn, x, name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win = unwrap(window) if window is not None else jnp.ones(win_length)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))

    def fn(s):
        squeeze = s.ndim == 2
        if squeeze:
            s = s[None]
        spec = jnp.swapaxes(s, 1, 2)  # [b, frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.float32(n_fft))
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided else \
            jnp.fft.ifft(spec, axis=-1).real
        frames = frames * win
        n = frames.shape[1]
        out_len = (n - 1) * hop_length + n_fft
        out = jnp.zeros((frames.shape[0], out_len), frames.dtype)
        wsum = jnp.zeros((out_len,), frames.dtype)
        for i in range(n):
            sl = slice(i * hop_length, i * hop_length + n_fft)
            out = out.at[:, sl].add(frames[:, i])
            wsum = wsum.at[sl].add(win * win)
        out = out / jnp.maximum(wsum, 1e-10)
        if center:
            out = out[:, n_fft // 2:-(n_fft // 2) or None]
        if length is not None:
            out = out[:, :length]
        return out[0] if squeeze else out

    return apply(fn, x, name="istft")
