"""Predictive fleet autoscaler: hysteresis over merged pressure.

The elasticity half of the fleet cache plane (serving/fleet_cache.py):
with routing cache-aware and KV pullable between pools, replicas are
finally fungible enough to add and remove mechanically. This module
closes that loop with a deliberately boring controller — the
``BrownoutController`` school (serving/overload.py): edge-triggered,
hysteresis on BOTH edges, every transition flight-recorded — over a
MERGED fleet pressure signal:

- per READY replica, the max of its overload pressure
  (``OverloadController.pressure``: queue watermark, binding-slice KV
  headroom, predicted wait), its raw queue fraction (so the signal
  exists even with the overload plane disarmed), and its brownout
  stage (stage s > 0 reads as ``1 + s/4`` — a browned-out replica IS
  over pressure by definition);
- fleet pressure = mean over READY replicas, floored to >= 1.0 when
  ANY requests were shed since the last tick (``serving.shed`` delta —
  shed traffic is the one signal that must never average away).

``update()`` is one evaluation tick (callers own the cadence: a
registrar beat hook, a gate loop, an operator cron). Sustained
pressure >= 1.0 for ``FLAGS_autoscale_enter_steps`` ticks spawns ONE
warm replica through the caller's ``spawn`` callback (an AOT-store
boot is zero-compile — serving/aot_cache.py), ``warmup()``s it if
still WARMING, and adds it to the router; sustained pressure <=
``FLAGS_autoscale_low`` for ``FLAGS_autoscale_exit_steps`` ticks
retires the least-loaded replica THIS controller spawned — never the
seed fleet — through the zero-drop drain contract
(``Router.drain`` -> ``remove_replica`` -> ``close``). In-band ticks
count ``holds``; both edges reset both accumulators, so a flapping
signal scales at most once per sustained excursion.

Counters: ``serving.autoscale.{scale_ups,scale_downs,holds}``.
``FLAGS_fleet_autoscale=0`` (default; read at construction, the
``FLAGS_serving_prefix_cache`` convention) makes ``update()`` a no-op
returning the current stage — zero counter movement, zero fleet
mutation (tools/fleet_cache_gate.py pins the silence).
"""

from __future__ import annotations

from ..core import flags as flags_mod
from ..core import resilience
from ..profiler import metrics as _metrics
from .frontend import Lifecycle

__all__ = ["FleetAutoscaler", "fleet_pressure"]

_c_scale_ups = _metrics.counter("serving.autoscale.scale_ups")
_c_scale_downs = _metrics.counter("serving.autoscale.scale_downs")
_c_holds = _metrics.counter("serving.autoscale.holds")
_g_size = _metrics.gauge("serving.autoscale.size")

_SHED = _metrics.counter("serving.shed")


def _replica_pressure(engine):
    sched = engine.scheduler
    p = 0.0
    ov = getattr(sched, "overload", None)
    if ov is not None:
        try:
            p = float(ov.pressure(sched))
        except Exception:  # noqa: BLE001 — a broken signal reads as calm;
            p = 0.0        # the queue fraction below still sees backlog
        bo = getattr(ov, "brownout", None)
        stage = getattr(bo, "stage", 0) if bo is not None else 0
        if stage:
            p = max(p, 1.0 + stage / 4.0)
    if sched.max_queue:
        p = max(p, len(sched.queue) / float(sched.max_queue))
    return p


def fleet_pressure(router):
    """Merged fleet pressure (module docstring): mean per-READY-replica
    pressure, >= 1.0 whenever the fleet shed since the last call site's
    tick handles the shed delta (see :meth:`FleetAutoscaler.update`)."""
    vals = []
    for rid in list(router._order):
        rep = router._replicas.get(rid)
        eng = rep.engine if rep is not None else None
        if eng is None or eng._error is not None \
                or eng.lifecycle != Lifecycle.READY:
            continue
        vals.append(_replica_pressure(eng))
    return sum(vals) / len(vals) if vals else 0.0


class FleetAutoscaler:
    """See module docstring. ``router`` is the fleet front door;
    ``spawn`` a zero-arg callable returning a fresh ``ServingEngine``
    (conventionally an AOT-store warm boot). ``pressure_fn`` overrides
    the merged signal (tests/gates inject deterministic pressure the
    way ``shed_tune`` pins watermarks); knob defaults read the
    ``FLAGS_autoscale_*`` family at construction."""

    def __init__(self, router, spawn, *, min_replicas=1,
                 max_replicas=None, enter_steps=None, exit_steps=None,
                 low_pressure=None, pressure_fn=None,
                 drain_timeout_s=60.0, rid_prefix="auto"):
        self._armed = bool(flags_mod.flag("FLAGS_fleet_autoscale"))
        self.router = router
        self._spawn = spawn
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(
            flags_mod.flag("FLAGS_autoscale_max_replicas")
            if max_replicas is None else max_replicas)
        self.enter_steps = int(
            flags_mod.flag("FLAGS_autoscale_enter_steps")
            if enter_steps is None else enter_steps)
        self.exit_steps = int(
            flags_mod.flag("FLAGS_autoscale_exit_steps")
            if exit_steps is None else exit_steps)
        self.low_pressure = float(
            flags_mod.flag("FLAGS_autoscale_low")
            if low_pressure is None else low_pressure)
        self._pressure_fn = pressure_fn
        self.drain_timeout_s = float(drain_timeout_s)
        self.rid_prefix = str(rid_prefix)
        self._spawned = {}  # replica_id -> engine (retirement set)
        self._seq = 0
        self._over = 0
        self._under = 0
        self._last_shed = _SHED.value

    # -- signals --------------------------------------------------------

    def pressure(self):
        """The signal one tick acts on: ``pressure_fn`` if injected,
        else :func:`fleet_pressure`, floored to 1.0 when requests were
        shed since the previous tick."""
        p = float(self._pressure_fn() if self._pressure_fn is not None
                  else fleet_pressure(self.router))
        shed = _SHED.value
        if shed > self._last_shed:
            p = max(p, 1.0)
        self._last_shed = shed
        return p

    def size(self):
        """Live engine-bound fleet size (what min/max bound)."""
        with self.router._lock:
            reps = list(self.router._replicas.values())
        return sum(1 for r in reps if r.engine is not None
                   and r.engine._error is None
                   and r.engine.lifecycle == Lifecycle.READY)

    # -- the control loop -----------------------------------------------

    def update(self):
        """One evaluation tick; returns ``"up"``, ``"down"``, or None
        (held). Disarmed: always None, no counters, no mutation."""
        if not self._armed:
            return None
        p = self.pressure()
        action = None
        if p >= 1.0:
            self._under = 0
            self._over += 1
            if self._over >= self.enter_steps:
                self._over = 0
                if self._scale_up(p):
                    action = "up"
        elif p <= self.low_pressure:
            self._over = 0
            self._under += 1
            if self._under >= self.exit_steps:
                self._under = 0
                if self._scale_down(p):
                    action = "down"
        else:
            # in-band: both accumulators reset — excursions must be
            # SUSTAINED, a dip through the band starts the count over
            self._over = 0
            self._under = 0
        if action is None:
            _c_holds.inc()
        _g_size.set(self.size())
        return action

    def _record(self, name, status, **meta):
        try:
            from ..distributed import watchdog
            watchdog.record_event(name, meta=meta, status=status)
        except Exception:  # noqa: BLE001 — flight recording is advisory
            pass

    def _scale_up(self, pressure):
        if self.size() >= self.max_replicas:
            return False  # at ceiling: the tick counts as a hold
        try:
            eng = self._spawn()
            if eng.lifecycle == Lifecycle.WARMING:
                eng.warmup()
            self._seq += 1
            rid = f"{self.rid_prefix}{self._seq}"
            self.router.add_replica(rid, engine=eng)
            self._spawned[rid] = eng
        except Exception as e:  # noqa: BLE001 — a failed spawn must not
            # kill the control loop; pressure will re-trigger the edge
            resilience.degrade("autoscale.spawn", exc=e)
            return False
        _c_scale_ups.inc()
        self._record("autoscale.scale_up", "degraded",
                     replica=rid, pressure=round(pressure, 4),
                     size=self.size())
        return True

    def _scale_down(self, pressure):
        victim = None
        for rid, eng in self._spawned.items():
            if eng._error is not None \
                    or eng.lifecycle != Lifecycle.READY:
                continue
            load = eng.scheduler.inflight()
            if victim is None or load < victim[1]:
                victim = (rid, load)
        if victim is None or self.size() <= self.min_replicas:
            return False  # nothing retirable: hold
        rid = victim[0]
        eng = self._spawned.pop(rid)
        try:
            # the PR 11 zero-drop contract: drain finishes in-flight
            # work while _candidates() already refuses the replica
            self.router.drain(rid, timeout=self.drain_timeout_s)
        except Exception as e:  # noqa: BLE001 — a wedged drain still
            # retires the replica from routing; close() below drains
            # again best-effort
            resilience.degrade("autoscale.drain", detail=f"replica={rid}",
                               exc=e)
        self.router.remove_replica(rid)
        eng.close()
        _c_scale_downs.inc()
        self._record("autoscale.scale_down", "recovered",
                     replica=rid, pressure=round(pressure, 4),
                     size=self.size())
        return True
