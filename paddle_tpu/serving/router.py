"""SLO-weighted multi-replica router: the fleet front door.

PR 11's fleet observatory made a set of serving replicas observable —
registry, health scores, drain-aware ``/readyz`` — but nothing
consumed it: telemetry was a dashboard, not a control loop. This
module closes the loop. A :class:`Router` sits in front of N replicas
and turns those signals into placement decisions:

- **discovery** — replicas are added directly (in-process
  ``ServingEngine``s — the topology every test/gate/fleet-demo in
  this repo runs) and/or discovered from the ``TCPStore`` fleet
  registry (``profiler/fleet.read_members``): a registry payload binds
  to the engine with the same ``replica_id`` and contributes its
  heartbeat age to the weight, so a replica whose heartbeat died
  routes toward zero BEFORE it formally ages out. Registry entries
  with no bound engine are visible in :meth:`view` and not directly
  submittable — but they DO qualify as remote decode-stage candidates
  (``stage_candidates(..., allow_remote=True)``): serving/disagg.py
  admits the handed-off request on them over rpc, leased + cursor-
  relayed, so the decode stage can live in another process;
- **readiness** — a replica that is not READY on the drain lifecycle
  (``/readyz`` semantics: WARMING, DRAINING, CLOSED, or dead) is
  refused outright: a drain REDISTRIBUTES, the draining replica
  finishes its in-flight work (zero dropped — the PR 11 drain
  contract) while new traffic lands elsewhere;
- **weighting** — candidates are ranked by
  ``health_score(snapshot) / (1 + inflight)``: the PURE fleet health
  formula (``profiler/fleet.health_score``: queue depth, KV headroom,
  heartbeat freshness) over the replica's live scheduler state,
  damped by its in-flight load — equal replicas round-robin, a
  degraded replica sheds traffic in proportion, a silent one goes to
  zero;
- **retry** — a failed submit (``NotReadyError``, ``QueueFullError``,
  a dead engine) moves to the next-best replica (counted
  ``router.retried``, degraded ``resilience.degrade.router.retry``);
  when every candidate refuses, the sweep retries under the
  ``core/resilience`` ``router.submit`` policy (jittered backoff)
  before :class:`NoReplicaAvailable` propagates (counted
  ``router.rejected``) — carrying per-replica refusal reasons and the
  smallest ``retry_after_s`` any structured rejection suggested;
- **circuit breakers** (``FLAGS_router_breaker``, read at
  construction) — each replica gets a
  ``core.resilience.CircuitBreaker``: repeated submit failures OPEN it
  and the sweep skips that replica outright (no submit attempt, no
  per-sweep hammering of a dying engine) until the reset window
  elapses and a single half-open probe request succeeds, which closes
  it. Counted ``router.breaker.{opened,closed,probes,skipped}``,
  opens degraded + flight-recorded;
- **failover** — if a replica DIES mid-flight (its requests
  terminate ``ERROR``), :class:`RoutedHandle` re-submits the request
  to the next-best replica (counted ``router.failover``, degraded +
  flight-recorded) up to ``FLAGS_router_max_failovers`` times. A
  request that reached ANY clean terminal status (DONE / CANCELLED /
  TIMEOUT) is NEVER re-submitted — every request lands exactly once
  (tests/framework/test_router.py drives the matrix under injected
  replica death).

Every routed submit records a ``serving.route`` span onto the
request's own trace (replica, attempt count, candidates), so a
request's journey — route -> queue -> prefill -> decode -> terminal —
reads as one trace. Counters: ``router.{routed,retried,failover,
rejected}``.

``FLAGS_serving_router=0`` (read at Router construction, the
``FLAGS_serving_accounting`` convention) makes the router a
byte-for-byte pass-through to its first replica's engine — identical
handles, zero ``router.*`` counter movement (tools/router_gate.py
pins the silence).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

from ..core import flags as flags_mod
from ..core import resilience
from ..profiler import fleet as _fleet
from ..profiler import metrics as _metrics
from ..profiler import tracing as _tracing
from ..testing import faults as _faults
from .frontend import Lifecycle, NotReadyError
from .scheduler import (AdmissionRejected, QueueFullError,
                        RequestStatus)

__all__ = ["Router", "RouterReplica", "RoutedHandle",
           "NoReplicaAvailable"]

_c_routed = _metrics.counter("router.routed")
_c_retried = _metrics.counter("router.retried")
_c_failover = _metrics.counter("router.failover")
_c_rejected = _metrics.counter("router.rejected")
_g_routable = _metrics.gauge("router.replicas.routable")


class NoReplicaAvailable(RuntimeError):
    """No READY replica accepted the request — shed load upstream or
    scale out. Diagnosable from the exception alone: ``reasons`` maps
    each considered ``replica_id`` to why it refused (``NoEngine``,
    ``NotReady(<state>)``, ``Dead``, ``breaker-open``, or the refusing
    exception's type name, e.g. ``QueueFullError`` /
    ``AdmissionRejected``), and ``retry_after_s`` carries the smallest
    back-off any structured rejection suggested (None when none
    did). Disaggregated two-stage sweeps (serving/disagg.py) add
    stage-level entries — ``no-prefill-replica`` /
    ``no-decode-replica`` / ``transfer-failed`` — so the exception
    alone says which stage starved."""

    def __init__(self, message, *, reasons=None, retry_after_s=None):
        self.reasons = dict(reasons or {})
        self.retry_after_s = retry_after_s
        if self.reasons:
            message += " [" + ", ".join(
                f"{rid}: {why}"
                for rid, why in sorted(self.reasons.items())) + "]"
        super().__init__(message)


class RouterReplica:
    """One replica as the router sees it: an in-process engine (the
    submit target), and/or a fleet-registry payload whose heartbeat
    age and state feed the weight."""

    __slots__ = ("replica_id", "engine", "url", "member", "_role")

    def __init__(self, replica_id, engine=None, url=None, member=None,
                 role=None):
        self.replica_id = str(replica_id)
        self.engine = engine
        self.url = url
        self.member = member  # latest fleet/member/<n> payload, if any
        self._role = None if role is None else str(role)

    @property
    def role(self):
        """Serving role for disaggregated placement: explicit value
        wins, else the fleet-registry payload, else the bound engine's
        own role, else ``"mixed"`` (a candidate for every stage — the
        pre-disaggregation default, so existing fleets are
        untouched)."""
        if self._role is not None:
            return self._role
        if self.member is not None and self.member.get("role"):
            return str(self.member["role"])
        if self.engine is not None:
            return getattr(self.engine, "role", "mixed")
        return "mixed"

    def ready(self):
        """READY on the drain lifecycle. In-process engines answer
        directly (what their /readyz serves); registry-only replicas
        answer by their last heartbeat state; url-only replicas get a
        real GET."""
        if self.engine is not None:
            return (self.engine.lifecycle == Lifecycle.READY
                    and self.engine._error is None)
        if self.member is not None:
            return self.member.get("state") == Lifecycle.READY
        if self.url:
            try:
                with urllib.request.urlopen(
                        self.url.rstrip("/") + "/readyz", timeout=2.0) as r:
                    return json.loads(r.read()).get("ready") is True
            except Exception:  # noqa: BLE001 — unreachable = not routable
                return False
        return False

    def snapshot(self):
        """The :func:`profiler.fleet.health_score` input, built from
        live scheduler state (queue depth, KV utilization) plus the
        registry heartbeat age when discovered via store."""
        snap = {}
        if self.engine is not None:
            sched = self.engine.scheduler
            cache = sched.cache
            usable = cache.num_blocks - 1
            used = usable - cache.num_free_blocks()
            snap["queue_depth"] = len(sched.queue)
            snap["kv_utilization"] = used / usable if usable else 0.0
        m = self.member
        if m is not None and "heartbeat_ts" in m:
            snap["heartbeat_age_s"] = max(
                time.time() - float(m["heartbeat_ts"]), 0.0)
            snap["ttl_s"] = float(m.get("ttl_s", 0.0))
        return snap

    def health(self):
        return _fleet.health_score(self.snapshot())

    def inflight(self):
        if self.engine is not None:
            return self.engine.scheduler.inflight()
        return 0


class RoutedHandle:
    """Caller-side view of one routed request. Forwards to the live
    replica's :class:`~paddle_tpu.serving.RequestHandle`; if that
    replica dies (status ``ERROR``), ``result()``/``stream()``
    transparently fail over to the next-best replica — a clean
    terminal status is final and never re-submitted."""

    __slots__ = ("_router", "_prompt", "_mnt", "_kw", "_replica",
                 "_handle", "_failovers", "_lock")

    def __init__(self, router, prompt, max_new_tokens, kw, replica,
                 handle):
        self._router = router
        self._prompt = prompt
        self._mnt = max_new_tokens
        self._kw = kw
        self._replica = replica
        self._handle = handle
        self._failovers = 0
        self._lock = threading.Lock()

    @property
    def replica_id(self):
        return self._replica.replica_id

    @property
    def status(self):
        return self._handle.status

    @property
    def rid(self):
        return self._handle.rid

    @property
    def trace_id(self):
        return self._handle.trace_id

    def tokens(self):
        return self._handle.tokens()

    def cost(self):
        return self._handle.cost()

    def cancel(self):
        self._handle.cancel()

    def result(self, timeout=None):
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        while True:
            left = None if deadline is None \
                else max(deadline - time.monotonic(), 0.001)
            try:
                return self._handle.result(timeout=left)
            except TimeoutError:
                raise
            except Exception as e:  # noqa: BLE001 — engine fatal error
                # Exception, NOT BaseException: a KeyboardInterrupt must
                # interrupt, never morph into a failover re-submit
                self._failover_or_raise(e)

    def stream(self, timeout=None):
        """Yield tokens like ``RequestHandle.stream``; on replica death
        the stream fails over and suppresses the re-generated prefix,
        so the caller sees each position exactly once (exact
        continuation relies on deterministic sampling — greedy
        decode, the same contract as preemption re-prefill)."""
        yielded = 0
        skip = 0
        while True:
            try:
                for tok in self._handle.stream(timeout=timeout):
                    if skip > 0:
                        skip -= 1
                        continue
                    yielded += 1
                    yield tok
                return
            except Exception as e:  # noqa: BLE001 — engine fatal error;
                # NOT BaseException: an abandoned generator's
                # GeneratorExit must close the stream, not re-submit
                # work the caller walked away from
                self._failover_or_raise(e)
                skip = yielded

    def _failover_or_raise(self, exc):
        """Re-submit ONLY a request whose replica died under it: clean
        terminal statuses are final (exactly-once), and the failover
        budget bounds a dying fleet."""
        with self._lock:
            h = self._handle
            if h.status != RequestStatus.ERROR:
                raise exc
            limit = int(flags_mod.flag("FLAGS_router_max_failovers"))
            if self._failovers >= limit:
                raise exc
            self._failovers += 1
            dead = self._replica.replica_id
            _c_failover.inc()
            resilience.degrade(
                "router.failover",
                detail=f"replica={dead} rid={h.rid} "
                       f"attempt={self._failovers}", exc=exc)
            self._replica, self._handle = self._router._submit_once(
                self._prompt, self._mnt, self._kw, exclude={dead})


class Router:
    """See module docstring. Thread-safe; construct once per front
    door. ``replicas`` is an iterable of :class:`RouterReplica` (or
    use :meth:`add_replica`); ``store`` opts into TCPStore registry
    discovery (rate-limited by ``min_refresh_s``, like the
    aggregator's sweep)."""

    def __init__(self, replicas=None, store=None, min_refresh_s=1.0):
        self._armed = bool(flags_mod.flag("FLAGS_serving_router"))
        # per-replica circuit breakers (core.resilience.CircuitBreaker,
        # read at construction like FLAGS_serving_router itself):
        # repeated submit failures open a replica's breaker and the
        # candidate sweep skips it until a half-open probe succeeds;
        # disarmed = no breaker objects at all, router.breaker.* silent
        self._breaker_armed = self._armed and bool(
            flags_mod.flag("FLAGS_router_breaker"))
        self._breakers = {}
        # fleet cache plane (serving/fleet_cache.py; FLAGS_fleet_cache
        # read here like FLAGS_serving_router itself): digest-aware
        # candidate ranking + peer KV pulls. Disarmed = no plane object
        # at all — placement stays byte-for-byte health-rank and
        # serving.fleet_cache.* never moves
        self.fleet_cache = None
        if self._armed and bool(flags_mod.flag("FLAGS_fleet_cache")):
            from . import fleet_cache as _fleet_cache
            self.fleet_cache = _fleet_cache.FleetCachePlane(self)
        self._lock = threading.Lock()
        self._replicas = {}
        self._order = []  # insertion order: the disarmed primary
        self.store = store if store is not None \
            and bool(flags_mod.flag("FLAGS_fleet")) else None
        self.min_refresh_s = float(min_refresh_s)
        self._scan_state = {}
        self._last_refresh = None
        for rep in replicas or []:
            self._add(rep)

    # -- membership -----------------------------------------------------

    def _add(self, rep):
        with self._lock:
            if rep.replica_id not in self._replicas:
                self._order.append(rep.replica_id)
            self._replicas[rep.replica_id] = rep

    def add_replica(self, replica_id, engine=None, url=None, role=None):
        """Register (or re-bind) a replica; returns its record. An
        engine bound to an already-discovered registry entry merges
        with it (the heartbeat keeps feeding the weight). ``role``
        pins the serving role explicitly (else it resolves from the
        registry payload / engine — see :attr:`RouterReplica.role`)."""
        with self._lock:
            rep = self._replicas.get(str(replica_id))
            if rep is not None:
                if engine is not None:
                    rep.engine = engine
                if url is not None:
                    rep.url = url
                if role is not None:
                    rep._role = str(role)
                return rep
        rep = RouterReplica(replica_id, engine=engine, url=url,
                            role=role)
        self._add(rep)
        return rep

    def remove_replica(self, replica_id):
        with self._lock:
            self._replicas.pop(str(replica_id), None)
            # drop the breaker too: a re-registered id must not inherit
            # a dead incarnation's open breaker, and churned ids must
            # not accumulate state across a lifetime of deploys
            self._breakers.pop(str(replica_id), None)
            try:
                self._order.remove(str(replica_id))
            except ValueError:
                pass

    def refresh(self, force=False):
        """Registry discovery sweep (rate-limited): bind fresh member
        payloads to known replicas by ``replica_id``; unknown ids
        appear as registry-only records (not submittable)."""
        if self.store is None:
            return
        now = time.monotonic()
        if not force and self._last_refresh is not None \
                and now - self._last_refresh < self.min_refresh_s:
            return
        self._last_refresh = now
        try:
            members = _fleet.read_members(self.store, self._scan_state)
        except Exception as e:  # noqa: BLE001 — a flaky store must not stop routing
            resilience.degrade("router.discovery", exc=e)
            return
        seen = set()
        for p in members:
            rid = str(p["replica_id"])
            seen.add(rid)
            with self._lock:
                rep = self._replicas.get(rid)
            if rep is None:
                rep = RouterReplica(rid, url=p.get("url"), member=p)
                self._add(rep)
            else:
                rep.member = p
        # a deregistered replica (drain/close deletes its entry) keeps
        # its LAST payload: a stale heartbeat_ts decays it to zero
        # weight, and an engine-bound record still answers ready()
        # directly — the registry's absence must not resurrect it
        return seen

    # -- placement ------------------------------------------------------

    def _candidates(self, exclude=(), reasons=None, stage=None,
                    allow_remote=False):
        """READY, engine-bound replicas ranked health-over-load.
        ``reasons`` (a dict, mutated) collects why every OTHER known
        replica was refused — the per-replica diagnosis
        :class:`NoReplicaAvailable` carries. ``stage`` (``"prefill"``
        / ``"decode"``, disaggregated serving) filters by role: a
        stage accepts same-role and ``mixed`` replicas, never the
        opposite specialist — a prefill-only replica must not take
        decode traffic and vice versa. ``allow_remote`` additionally
        admits ENGINE-LESS replicas that answer :meth:`RouterReplica.
        ready` (registry heartbeat state / a live ``/readyz``) — the
        cross-process decode candidates serving/disagg.py admits over
        rpc; plain submits never set it (an engine-less replica cannot
        take a local submit)."""
        self.refresh()
        with self._lock:
            reps = [self._replicas[rid] for rid in self._order
                    if rid not in exclude]
        cands = []
        for r in reps:
            if stage is not None and r.role not in ("mixed", stage):
                if reasons is not None:
                    reasons[r.replica_id] = f"WrongRole({r.role})"
            elif r.engine is None:
                if allow_remote and r.ready():
                    cands.append(r)
                elif reasons is not None:
                    reasons[r.replica_id] = (
                        "NotReady(remote)" if allow_remote
                        else "NoEngine")
            elif not r.ready():
                if reasons is not None:
                    reasons[r.replica_id] = (
                        "Dead" if r.engine._error is not None
                        else f"NotReady({r.engine.lifecycle})")
            else:
                cands.append(r)
        if stage is None:
            _g_routable.set(len(cands))
        # health over load: equal replicas round-robin via the inflight
        # damping, a zero-health (silent/burning) replica sorts last
        cands.sort(key=lambda r: -(r.health() / (1.0 + r.inflight())))
        return cands

    def stage_candidates(self, stage, exclude=(), reasons=None,
                         allow_remote=False):
        """Ranked candidates for one disaggregation stage
        (``"prefill"`` / ``"decode"``): the :meth:`_candidates` sweep
        with role filtering. serving/disagg.py's two-stage pipeline
        calls this once per stage and carries the refusal reasons into
        its stage-keyed :class:`NoReplicaAvailable`; it sets
        ``allow_remote`` for the decode stage when its transport can
        admit cross-process (engine-less registry/url replicas then
        qualify — see :meth:`_candidates`)."""
        return self._candidates(exclude=exclude, reasons=reasons,
                                stage=str(stage),
                                allow_remote=bool(allow_remote))

    def _breaker(self, replica_id):
        b = self._breakers.get(replica_id)
        if b is None:
            with self._lock:
                b = self._breakers.setdefault(
                    replica_id, resilience.CircuitBreaker(
                        f"router.{replica_id}",
                        counter_prefix="router.breaker"))
        return b

    def _submit_once(self, prompt, max_new_tokens, kw, exclude=()):
        t0 = time.perf_counter_ns()
        reasons = {}
        cands = self._candidates(exclude, reasons)
        view = None
        if self.fleet_cache is not None and cands:
            # digest-aware re-rank (fails open to the health order);
            # the view carries the per-advertiser coverage the
            # peer-fill step below reuses — digests computed ONCE
            cands, view = self.fleet_cache.rank(cands, prompt)
        retry_after = None
        for i, rep in enumerate(cands):
            br = self._breaker(rep.replica_id) \
                if self._breaker_armed else None
            if br is not None:
                try:
                    _faults.site("router.breaker")
                    allowed = br.allow()
                except Exception as e:  # noqa: BLE001 — fail OPEN: a broken
                    # breaker must not stop routing to a healthy replica
                    resilience.degrade(
                        "router.breaker",
                        detail=f"replica={rep.replica_id}", exc=e)
                    allowed = True
                if not allowed:
                    reasons[rep.replica_id] = "breaker-open"
                    continue
            pull = None
            if view is not None:
                # peer fill BEFORE submit: a failed submit strands at
                # worst a parked refcount-0 import in this replica's
                # reclaimable LRU (evictable, admissible by anyone);
                # submitting first would race the background driver's
                # admission past the pull
                pull = self.fleet_cache.peer_fill(rep, view)
            try:
                _faults.site("router.submit")
                _faults.site(f"router.submit.{rep.replica_id}")
                h = rep.engine.submit(prompt, max_new_tokens, **kw)
            except (NotReadyError, QueueFullError,
                    RuntimeError) as e:
                _c_retried.inc()
                resilience.degrade(
                    "router.retry",
                    detail=f"replica={rep.replica_id}", exc=e)
                reasons[rep.replica_id] = type(e).__name__
                ra = getattr(e, "retry_after_s", None)
                if ra is not None:
                    retry_after = ra if retry_after is None \
                        else min(retry_after, ra)
                # the breaker isolates FAILING replicas, not busy ones:
                # structured policy rejections (not-ready lifecycle,
                # queue backpressure, overload admission) come from a
                # HEALTHY engine doing its job — opening on them would
                # blackhole the top-priority traffic the replica still
                # accepts. Only unexpected failures count; a policy
                # refusal releases any consumed half-open probe slot
                # (no verdict) so recovery can never wedge behind it.
                if br is not None:
                    if isinstance(e, (NotReadyError, QueueFullError,
                                      AdmissionRejected)):
                        br.release_probe()
                    elif br.record_failure():
                        resilience.degrade(
                            "router.breaker.open",
                            detail=f"replica={rep.replica_id} after "
                                   f"{br.failure_threshold} failures")
                continue
            except BaseException:
                # caller-side errors (e.g. a validation ValueError)
                # propagate untouched — but never leak a consumed
                # probe slot on the way out
                if br is not None:
                    br.release_probe()
                raise
            if br is not None:
                # a half-open probe that lands here closes the breaker
                # (router.breaker.closed counts the edge)
                br.record_success()
            _c_routed.inc()
            req = getattr(h, "_req", None)
            if req is not None:
                _tracing.record_span(
                    "serving.route", req.span,
                    (time.perf_counter_ns() - t0) / 1000.0,
                    replica=rep.replica_id, attempt=i + 1,
                    candidates=len(cands))
            if view is not None:
                # coverage-hit counting + pull billing/span
                self.fleet_cache.note_routed(rep, h, view, pull)
            return rep, h
        raise NoReplicaAvailable(
            f"router: no READY replica accepted the request "
            f"({len(cands)} candidate(s), {len(exclude)} excluded)",
            reasons=reasons, retry_after_s=retry_after)

    def submit(self, prompt_ids, max_new_tokens=32, **kw):
        """Route one request; returns a :class:`RoutedHandle` (or,
        disarmed, the primary engine's plain handle). Sweeps refused
        by every candidate retry under the ``router.submit``
        resilience policy before :class:`NoReplicaAvailable`."""
        if not self._armed:
            return self._primary().engine.submit(
                prompt_ids, max_new_tokens, **kw)
        out = None
        try:
            pol = resilience.policy("router.submit", max_attempts=3,
                                    retry_on=(NoReplicaAvailable,))
            for attempt in resilience.attempts(pol):
                with attempt:
                    out = self._submit_once(prompt_ids, max_new_tokens,
                                            kw)
        except NoReplicaAvailable:
            _c_rejected.inc()
            raise
        rep, h = out
        return RoutedHandle(self, prompt_ids, max_new_tokens, kw, rep, h)

    def _primary(self):
        with self._lock:
            for rid in self._order:
                rep = self._replicas[rid]
                if rep.engine is not None:
                    return rep
        raise NoReplicaAvailable("router: no replica has an engine")

    # -- operations -----------------------------------------------------

    def drain(self, replica_id, timeout=60):
        """Drain one replica through the PR 11 contract: its in-flight
        requests finish (zero dropped), its readiness flips, and —
        because :meth:`_candidates` refuses non-READY replicas — new
        traffic redistributes to the rest. The record stays (a closed
        replica scores unroutable); ``remove_replica`` forgets it."""
        with self._lock:
            rep = self._replicas.get(str(replica_id))
        if rep is None or rep.engine is None:
            raise KeyError(f"router: no engine for replica "
                           f"{replica_id!r}")
        rep.engine.drain(timeout=timeout)

    def view(self):
        """Observability body: every known replica's readiness, health,
        and load — what a /router/replicas endpoint would serve."""
        self.refresh()
        with self._lock:
            reps = [self._replicas[rid] for rid in self._order]
        return [{"replica_id": r.replica_id,
                 "submittable": r.engine is not None,
                 "ready": r.ready(), "health": r.health(),
                 "inflight": r.inflight(), "role": r.role,
                 "state": (r.engine.lifecycle if r.engine is not None
                           else (r.member or {}).get("state"))}
                for r in reps]
