"""Persistent AOT compile cache: zero-cold-start process boots.

Warm-path serving is zero-recompile (serving/bucketing.py bounds the
program set; tools/serving_gate.py pins it) — but every FRESH process
still pays full XLA compilation per prefill bucket + the decode step,
which is fatal for rolling deploys and elastic scale-out: a replica
joining the fleet burns seconds of compile before its first token.
This module makes compilation a one-time fleet cost instead of a
per-process cost:

- the serving-path jit entry points (``Llama.paged_prefill`` /
  ``paged_prefill_extend`` / ``paged_decode_step``, and the
  deferred-chain programs under the ``passes/v1|v2`` / verbatim
  namespaces in ``core/deferred.py``) are wrapped in
  :class:`AOTFunction`, which dispatches per argument signature and —
  instead of letting ``jax.jit`` trace+compile on first call — runs
  ``jitted.lower(*args)`` (a pure python trace, no XLA), fingerprints
  the lowered module, and either **loads** a serialized executable
  from the on-disk store (``jax.experimental.serialize_executable``,
  zero backend compiles) or **compiles and stores** it for the next
  process;
- the **fingerprint** is git-sha-independent and content-addressed:
  blake2b over the jax version, the backend signature
  (platform/device-kind/device-count), the compilation-relevant jax
  config (x64, default matmul precision), a caller tag, and the full
  lowered StableHLO text — which itself encodes the jaxpr, every
  aval, and every flag that changed the traced program (the fusion /
  passes flags produce different HLO, hence different entries). Two
  processes that would compile the same program hash to the same
  entry; anything else misses;
- entries follow the **checkpoint-v2 durability discipline**
  (distributed/checkpoint.py): payloads are crc32-guarded, written to
  a private ``.tmp.<pid>`` staging file, fsynced, and
  ``os.replace``d into place — a crashed writer leaves no torn entry.
  A corrupt/truncated/foreign entry **quarantines** to
  ``*.corrupt-N`` (counted ``jit.aot.quarantined``, degraded
  ``resilience.degrade.aot_cache.corrupt``) and falls back to a
  normal compile that re-stores a fresh entry — a wrong executable is
  never loaded, and the failure mode is "pay the compile", never
  "serve garbage".

Telemetry rides the always-on registry: ``jit.aot.{hits,misses,
stores,quarantined}`` counters, ``jit.aot.bytes`` (payload bytes
moved), ``jit.aot.load_us`` (deserialize latency), and
``jit.aot.saved_us`` — the compile seconds each hit did NOT pay,
read back from the entry's recorded compile time. A thread-local
mirror (:func:`thread_saved_seconds`, the ``metrics.
thread_compile_seconds`` pattern) lets the serving scheduler bill
per-request compile-seconds-saved into PR 9's cost attribution
(``CostReport.aot_saved_us``) without touching the closure property.
``profiler.summary()`` renders the family as the "Cold start" view.

Arming: ``FLAGS_serving_aot_cache`` (default on) AND a non-empty
``FLAGS_aot_cache_dir`` (or ``PADDLE_TPU_AOT_CACHE`` env). Disarmed,
:class:`AOTFunction` forwards straight to the wrapped ``jax.jit``
callable — byte-for-byte the pre-cache behavior with every
``jit.aot.*`` counter silent (tools/router_gate.py pins it).

Fault sites (testing/faults.py; catalog in docs/ROBUSTNESS.md):
``aot.load`` fires before a store read (an injected failure falls
back to a normal compile — degraded, never fatal), ``aot.store``
before a store write (serving keeps the compiled program in hand).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import struct
import threading
import time
import zlib

from ..core import flags as flags_mod
from ..core import resilience
from ..profiler import metrics as _metrics
from ..testing import faults as _faults

__all__ = ["AOTFunction", "wrap", "armed", "cache_dir", "configure",
           "fingerprint", "thread_saved_seconds", "entry_path",
           "FORMAT", "MAGIC"]

MAGIC = b"PTPUAOT1"
FORMAT = 1
# MAGIC(8) + crc32(4) + payload length(8)
_HEADER = struct.Struct(">4sQ")

_c_hits = _metrics.counter("jit.aot.hits")
_c_misses = _metrics.counter("jit.aot.misses")
_c_stores = _metrics.counter("jit.aot.stores")
_c_quarantined = _metrics.counter("jit.aot.quarantined")
_c_bytes = _metrics.counter("jit.aot.bytes")
_c_saved_us = _metrics.counter("jit.aot.saved_us")
_h_load_us = _metrics.histogram(
    "jit.aot.load_us",
    bounds=(100, 500, 1000, 5000, 10000, 50000, 100000, 500000))

# compile seconds NOT paid by this thread thanks to cache hits — the
# per-thread delta discipline of metrics.thread_compile_seconds, so the
# scheduler can bill savings to the exact request whose dispatch hit
_tls = threading.local()


def thread_saved_seconds():
    """Cumulative compile seconds saved by AOT hits on the calling
    thread (0.0 before any hit)."""
    return getattr(_tls, "saved", 0.0)


def _note_saved(compile_s):
    _tls.saved = getattr(_tls, "saved", 0.0) + compile_s
    _c_saved_us.inc(compile_s * 1e6)


# -- arming ----------------------------------------------------------------

_armed_memo = (-1, False)


def armed():
    """True iff the cache may touch disk: ``FLAGS_serving_aot_cache``
    on AND ``FLAGS_aot_cache_dir`` non-empty. Memoized per flags epoch
    (one int compare on the warm path)."""
    global _armed_memo
    ep = flags_mod.epoch()
    memo = _armed_memo
    if memo[0] == ep:
        return memo[1]
    on = bool(flags_mod.flag("FLAGS_serving_aot_cache")) and \
        bool(flags_mod.flag("FLAGS_aot_cache_dir"))
    _armed_memo = (ep, on)
    return on


def cache_dir():
    """The configured store directory ('' when disarmed by dir)."""
    return os.path.expanduser(str(flags_mod.flag("FLAGS_aot_cache_dir")))


def configure(path):
    """Point the cache at ``path`` (the ``set_flags`` form — tests and
    operators; '' disarms)."""
    flags_mod.set_flags({"FLAGS_aot_cache_dir": "" if path is None
                         else str(path)})


# -- fingerprinting --------------------------------------------------------

def _backend_sig():
    try:
        import jax
        d = jax.devices()[0]
        return (f"{d.platform}/{getattr(d, 'device_kind', '?')}"
                f"x{jax.device_count()}")
    except Exception:  # noqa: BLE001 — a backendless probe still keys
        return "unknown"


def _config_sig():
    """Compilation-relevant jax config values that do NOT show up in
    the lowered text (x64 changes avals — belt and braces — matmul
    precision changes the compiled code, not the StableHLO)."""
    try:
        import jax
        return (f"x64={bool(jax.config.jax_enable_x64)};"
                f"mm={jax.config.jax_default_matmul_precision}")
    except Exception:  # noqa: BLE001
        return "cfg-unknown"


def fingerprint(tag, lowered_text):
    """Content address of one executable: jax version + backend +
    config + tag + the full lowered StableHLO text (jaxpr, avals, and
    every trace-visible flag are inside the text). Deterministic
    across processes — the cross-process reuse contract pinned by
    tools/router_gate.py."""
    import jax
    h = hashlib.blake2b(digest_size=20)
    for part in (jax.__version__, _backend_sig(), _config_sig(),
                 str(tag), lowered_text):
        h.update(part.encode())
        h.update(b"\x00")
    return h.hexdigest()


def entry_path(fp):
    return os.path.join(cache_dir(), fp + ".aotx")


# -- the on-disk store (checkpoint-v2 discipline) --------------------------

class _Corrupt(RuntimeError):
    """Entry failed validation — quarantine, never load."""


def _quarantine(path, why):
    """Rename a bad entry to ``*.corrupt-N`` (first free N — the
    checkpoint.py quarantine idiom) so the slot frees for a fresh
    store and the evidence survives for a post-mortem."""
    for n in range(1000):
        dst = f"{path}.corrupt-{n}"
        if not os.path.exists(dst):
            break
    try:
        os.replace(path, dst)
    except OSError:
        try:
            os.remove(path)
        except OSError:
            pass
    _c_quarantined.inc()
    resilience.degrade("aot_cache.corrupt",
                       detail=f"{os.path.basename(path)}: {why}")


def _load(fp):
    """Deserialize the entry for ``fp``; (compiled, meta) or (None,
    None) on miss. Validation failures quarantine and miss; transient
    I/O failures degrade and miss — both fall back to a normal
    compile, a wrong executable is never returned."""
    path = entry_path(fp)
    try:
        _faults.site("aot.load")
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return None, None
    except Exception as e:  # noqa: BLE001 — transient IO: compile instead
        resilience.degrade("aot_cache.load", exc=e)
        return None, None
    t0 = time.perf_counter_ns()
    try:
        if len(raw) < len(MAGIC) + _HEADER.size:
            raise _Corrupt(f"short file ({len(raw)}B)")
        if raw[:len(MAGIC)] != MAGIC:
            raise _Corrupt("bad magic")
        crc_b, length = _HEADER.unpack_from(raw, len(MAGIC))
        payload = raw[len(MAGIC) + _HEADER.size:]
        if len(payload) != length:
            raise _Corrupt(f"length {len(payload)} != header {length}")
        if zlib.crc32(payload) != int.from_bytes(crc_b, "big"):
            raise _Corrupt("crc32 mismatch")
        meta = pickle.loads(payload)
        if not isinstance(meta, dict) or meta.get("format") != FORMAT \
                or meta.get("fingerprint") != fp:
            raise _Corrupt("metadata disagrees with filename")
        from jax.experimental import serialize_executable as _se
        compiled = _se.deserialize_and_load(
            meta["exe"], meta["in_tree"], meta["out_tree"])
    except Exception as e:  # noqa: BLE001 — ANY load failure quarantines:
        # the entry claimed this fingerprint and could not deliver it
        _quarantine(path, f"{type(e).__name__}: {e}")
        return None, None
    _h_load_us.observe((time.perf_counter_ns() - t0) / 1000.0)
    _c_bytes.inc(len(raw))
    return compiled, meta


def _store(fp, compiled, compile_s, tag):
    """Serialize + commit one entry: staged write, fsync, atomic
    ``os.replace`` — a crashed writer leaves a ``.tmp`` straggler,
    never a torn entry. Failures degrade and return; the caller keeps
    the compiled program either way."""
    path = entry_path(fp)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        _faults.site("aot.store")
        from jax.experimental import serialize_executable as _se
        exe, in_tree, out_tree = _se.serialize(compiled)
        payload = pickle.dumps(
            {"format": FORMAT, "fingerprint": fp, "tag": str(tag),
             "compile_s": float(compile_s), "ts": time.time(),
             "backend": _backend_sig(), "exe": exe,
             "in_tree": in_tree, "out_tree": out_tree})
        os.makedirs(cache_dir(), exist_ok=True)
        blob = (MAGIC
                + _HEADER.pack(zlib.crc32(payload).to_bytes(4, "big"),
                               len(payload))
                + payload)
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except Exception as e:  # noqa: BLE001 — a full disk must not kill serving
        resilience.degrade("aot_cache.store", exc=e)
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False
    _c_stores.inc()
    _c_bytes.inc(len(blob))
    return True


# -- the wrapper -----------------------------------------------------------

def _leaf_sig(leaf):
    shp = getattr(leaf, "shape", None)
    if shp is None:
        # python scalars trace to value-independent weak avals: keying
        # by type keeps one entry per scalar KIND, not per value
        return ("py", type(leaf).__name__)
    return (tuple(shp), str(getattr(leaf, "dtype", "?")),
            bool(getattr(leaf, "weak_type", False)))


def _sig(args):
    # armed-path dispatch cost: a python tree_flatten + per-leaf tuple
    # per call (tens of µs on a real model's param list) against
    # millisecond-scale prefill/decode dispatches. Deliberate: an
    # identity/try-call fast path would have to catch aval mismatches
    # from Compiled, trading a measured overhead for a correctness
    # cliff; disarmed callers never reach here
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (treedef, tuple(_leaf_sig(leaf) for leaf in leaves))


class AOTFunction:
    """Shape-dispatching wrapper over a ``jax.jit`` callable.

    Disarmed (the production default until a cache dir is configured)
    every call forwards straight to the wrapped jitted function —
    plain-jax behavior, zero counters. Armed, calls dispatch on the
    argument signature (pytree structure + per-leaf shape/dtype/
    weak-type) to a per-process table of loaded executables; a novel
    signature lowers (python trace only), fingerprints, and loads-or-
    compiles through the on-disk store. Safe to call from multiple
    threads (the prepare step is locked; compiled executables are
    reusable concurrently, like jitted functions)."""

    __slots__ = ("_jitted", "tag", "_compiled", "_lock")

    def __init__(self, jitted, tag):
        self._jitted = jitted
        self.tag = str(tag)
        self._compiled = {}
        self._lock = threading.Lock()

    def __call__(self, *args):
        if not armed():
            return self._jitted(*args)
        key = _sig(args)
        compiled = self._compiled.get(key)
        if compiled is None:
            compiled = self._prepare(key, args)
        return compiled(*args)

    def _prepare(self, key, args):
        with self._lock:
            compiled = self._compiled.get(key)
            if compiled is not None:
                return compiled
            lowered = self._jitted.lower(*args)
            fp = fingerprint(self.tag, lowered.as_text())
            compiled, meta = _load(fp)
            if compiled is not None:
                _c_hits.inc()
                _note_saved(float(meta.get("compile_s", 0.0)))
            else:
                _c_misses.inc()
                t0 = time.perf_counter_ns()
                compiled = lowered.compile()
                compile_s = (time.perf_counter_ns() - t0) / 1e9
                _store(fp, compiled, compile_s, self.tag)
            self._compiled[key] = compiled
            return compiled


def wrap(jitted, tag):
    """Wrap a ``jax.jit`` callable for persistent AOT caching. Always
    returns an :class:`AOTFunction`; the per-call armed check makes
    the wrapper behave exactly like ``jitted`` until a cache dir is
    configured (and again the moment ``FLAGS_serving_aot_cache=0``)."""
    return AOTFunction(jitted, tag)
