"""Overload control plane: deadline-aware admission, priority load
shedding, and a brownout ladder over the serving scheduler.

Before this module the serving stack's only defense against overload
was the bounded FCFS queue (``QueueFullError`` at
``FLAGS_serving_max_queue``): a request whose deadline was provably
unmeetable still queued, paid its prefill, and only then hit TIMEOUT at
a step boundary — wasted device time exactly when the engine could
least afford it. This module turns the signals the observability PRs
built (per-token prefill/decode costs from the accounting axes, KV
occupancy from ``PagedKVCache.occupancy()``, queue depth) into the
load-shedding control loop a production front door needs:

- **Deadline-aware admission** (``FLAGS_serving_admission``). A
  :class:`ServiceTimeModel` keeps EWMAs of the per-token prefill cost
  and per-step decode cost — the same measured quantities
  ``profiler/accounting.py`` apportions, observed compile-free at each
  dispatch. At ``submit()`` it predicts queue-wait + TTFT; a request
  whose ``deadline_s`` cannot be met even at
  ``FLAGS_admission_optimism`` times the prediction (0.5: even HALF
  the predicted TTFT busts the deadline) is rejected immediately with
  :class:`AdmissionRejected` carrying a ``retry_after_s`` estimate —
  fail fast, never pay prefill for a corpse. The model only rejects
  once primed (a handful of observed prefills), so a cold engine
  admits everything.

- **Priority load shedding** (same flag). ``submit(priority=)`` takes
  an int class — smaller is more important (:data:`HIGH` = 0,
  :data:`NORMAL` = 1 the default, :data:`LOW` = 2; any int works).
  Each step the controller computes an overload **pressure** (max of
  queue-depth vs ``FLAGS_shed_queue_frac``·max_queue, KV occupancy vs
  ``FLAGS_shed_kv_frac``, predicted queue wait vs ``FLAGS_shed_wait_s``
  — all zero below the ``FLAGS_shed_min_queue`` backlog floor: a full
  pool with an empty queue is a busy engine keeping up, not overload).
  At pressure >= 1.0 the scheduler sheds **lowest-priority, newest
  queued** requests (the top class is never watermark-shed) to the
  terminal status ``SHED`` — blocks never allocated, handle carries
  ``retry_after_s`` — until pressure drops or only the top class
  remains. Preemption victim choice becomes priority-then-newest.

- **Brownout ladder** (``FLAGS_serving_brownout``). An edge-triggered,
  hysteresis-guarded controller (the ``profiler/alerts.py`` school)
  walks ordered stages under SUSTAINED pressure — stage 1 clamps
  effective ``max_new_tokens`` to ``FLAGS_brownout_clamp_tokens``,
  stage 2 rejects below-NORMAL submits, stage 3 admits only the top
  class — entering after ``FLAGS_brownout_enter_steps`` consecutive
  over-pressure steps and exiting (deliberately slower) after
  ``FLAGS_brownout_exit_steps`` steps at or below
  ``FLAGS_brownout_exit_pressure``. The current rung is the
  ``serving.brownout.stage`` gauge; every transition is counted and
  flight-recorded.

Both flags are read at Scheduler construction (the
``FLAGS_serving_accounting`` convention); with both off the scheduler
holds the preallocated :data:`NULL` controller — every hook a no-op,
behavior byte-for-byte pre-overload, ``serving.shed`` /
``serving.admission.*`` / ``serving.brownout.*`` counters silent
(``tools/overload_gate.py`` pins the revert). Survivors of a shedding
run stay greedy bit-identical to an uncontended run: shedding only
ever removes QUEUED requests (no slot, no blocks), so the PR 5/8
preemption pin extends unchanged.

Scope note: like every ``serving.*`` metric, the stage gauge and
counters are process-global — several engines in one process share
the family (the AlertManager caveat, docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import time

from ..core import flags as flags_mod
from ..core import resilience
from ..profiler import metrics as _metrics
from ..testing import faults as _faults

__all__ = ["AdmissionRejected", "ServiceTimeModel", "BrownoutController",
           "OverloadController", "NULL", "HIGH", "NORMAL", "LOW"]

# priority classes: smaller = more important (any int is accepted; these
# are the named rungs the brownout ladder gates against)
HIGH = 0
NORMAL = 1
LOW = 2

_US_BOUNDS = (500, 1000, 2500, 5000, 10000, 25000, 50000, 100000,
              250000, 500000, 1000000, 5000000)
_m_adm_rejected = _metrics.counter("serving.admission.rejected")
_m_clamped = _metrics.counter("serving.brownout.clamped")
_m_transitions = _metrics.counter("serving.brownout.transitions")
_g_stage = _metrics.gauge("serving.brownout.stage")
_h_pred_ttft = _metrics.histogram("admission.predicted_ttft_us",
                                  bounds=_US_BOUNDS)


class AdmissionRejected(RuntimeError):
    """Submission refused by the overload control plane — before any
    queueing or prefill. Structured like the new ``QueueFullError``:
    the caller (or the router) reads the fields instead of parsing the
    message. ``reason`` is ``"deadline"`` (the EWMA model proved the
    deadline unmeetable) or ``"brownout"`` (the ladder's current stage
    rejects this priority class); ``retry_after_s`` estimates when a
    retry could be admitted (None when the model is unprimed)."""

    def __init__(self, message, *, reason, retry_after_s=None,
                 predicted_ttft_s=None, deadline_s=None,
                 queue_depth=None, priority=None, stage=None):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.predicted_ttft_s = predicted_ttft_s
        self.deadline_s = deadline_s
        self.queue_depth = queue_depth
        self.priority = priority
        self.stage = stage


class ServiceTimeModel:
    """EWMA service-time model: per-token prefill cost and per-step
    decode cost, observed COMPILE-FREE (the scheduler subtracts the
    per-thread compile-seconds delta around each dispatch, the
    accounting discipline) so one cold bucket never poisons the
    steady-state estimate. Predictions are deliberately simple and
    documented — a drain-time estimate, not a simulation — and the
    admission path divides by ``FLAGS_admission_optimism`` worth of
    slack before trusting them."""

    __slots__ = ("alpha", "min_samples", "prefill_us_per_token",
                 "decode_step_us", "n_prefill", "n_decode")

    def __init__(self, alpha=0.2, min_samples=3):
        self.alpha = float(alpha)
        self.min_samples = int(min_samples)
        self.prefill_us_per_token = None
        self.decode_step_us = None
        self.n_prefill = 0
        self.n_decode = 0

    @property
    def primed(self):
        """Enough observations to base a REJECTION on. Predictions are
        served regardless (the histogram wants them); refusals wait."""
        return self.n_prefill >= self.min_samples

    def _ewma(self, old, sample):
        return sample if old is None else \
            old + self.alpha * (sample - old)

    def observe_prefill(self, tokens, us):
        """One prefill dispatch computed ``tokens`` (padded) in ``us``
        of compile-free wall time."""
        rate = float(us) / max(int(tokens), 1)
        self.prefill_us_per_token = \
            self._ewma(self.prefill_us_per_token, rate)
        self.n_prefill += 1

    def observe_decode(self, us):
        """One batched decode step took ``us`` compile-free."""
        self.decode_step_us = self._ewma(self.decode_step_us, float(us))
        self.n_decode += 1

    def predict(self, queued_tokens, queued_requests, own_tokens):
        """(predicted queue-wait us, predicted TTFT us) for a request
        arriving behind ``queued_requests`` requests totalling
        ``queued_tokens`` estimated-uncovered prefill tokens, itself
        needing ``own_tokens``. Queue drain = everyone ahead's prefill
        plus one interleaved decode step per queued request (the
        budgeted-admission cadence); TTFT adds this request's own
        prefill and its first decode interleave."""
        ppt = self.prefill_us_per_token or 0.0
        step = self.decode_step_us or 0.0
        wait_us = queued_tokens * ppt + queued_requests * step
        ttft_us = wait_us + max(own_tokens, 1) * ppt + step
        return wait_us, ttft_us


class BrownoutController:
    """The ordered degradation ladder: stage 0 (normal) .. 3 (top
    priority only). Edge-triggered with hysteresis — escalation needs
    ``enter_steps`` CONSECUTIVE over-pressure updates, de-escalation
    ``exit_steps`` consecutive updates at or below ``exit_pressure``,
    and the band between exit_pressure and 1.0 holds the stage (both
    counters reset on any interruption, so a flapping signal never
    walks the ladder). Each transition moves the
    ``serving.brownout.stage`` gauge, counts
    ``serving.brownout.transitions``, and lands a flight record, so a
    post-mortem shows exactly when service degraded and recovered."""

    MAX_STAGE = 3

    __slots__ = ("enter_steps", "exit_steps", "exit_pressure", "stage",
                 "_over", "_under")

    def __init__(self, enter_steps=None, exit_steps=None,
                 exit_pressure=None):
        self.enter_steps = (
            int(flags_mod.flag("FLAGS_brownout_enter_steps"))
            if enter_steps is None else int(enter_steps))
        self.exit_steps = (
            int(flags_mod.flag("FLAGS_brownout_exit_steps"))
            if exit_steps is None else int(exit_steps))
        self.exit_pressure = (
            float(flags_mod.flag("FLAGS_brownout_exit_pressure"))
            if exit_pressure is None else float(exit_pressure))
        self.stage = 0
        self._over = 0
        self._under = 0

    def update(self, pressure):
        """One evaluation (the scheduler calls it per step). Returns
        the (possibly changed) stage."""
        if pressure >= 1.0:
            self._under = 0
            self._over += 1
            if self._over >= self.enter_steps \
                    and self.stage < self.MAX_STAGE:
                self._transition(self.stage + 1, pressure)
                self._over = 0
        elif pressure <= self.exit_pressure:
            self._over = 0
            self._under += 1
            if self._under >= self.exit_steps and self.stage > 0:
                self._transition(self.stage - 1, pressure)
                self._under = 0
        else:
            # hysteresis band: hold the stage, restart both windows
            self._over = 0
            self._under = 0
        return self.stage

    def _transition(self, to, pressure):
        frm, self.stage = self.stage, to
        _g_stage.set(to)
        _m_transitions.inc()
        try:
            from ..distributed import watchdog
            watchdog.record_event(
                "brownout.stage",
                meta={"from": frm, "to": to,
                      "pressure": round(float(pressure), 3)},
                status="degraded" if to > frm else "recovered")
        except Exception:  # noqa: BLE001 — telemetry must not block control
            pass


class OverloadController:
    """Per-scheduler control plane: owns the service-time model, the
    pressure computation, the shed policy, and (optionally) the
    brownout ladder. The scheduler drives it: ``observe_*`` at each
    dispatch, ``control`` once per step (before admission), ``admit``
    at each submit. NOT thread-safe by itself — the frontend's engine
    lock serializes, like the Accountant."""

    armed = True

    def __init__(self, admission=True, brownout=True, model=None):
        self.shedding = bool(admission)
        self.model = model if model is not None else ServiceTimeModel()
        self.optimism = float(flags_mod.flag("FLAGS_admission_optimism"))
        self.min_queue = int(flags_mod.flag("FLAGS_shed_min_queue"))
        self.queue_frac = float(flags_mod.flag("FLAGS_shed_queue_frac"))
        self.kv_frac = float(flags_mod.flag("FLAGS_shed_kv_frac"))
        self.wait_s = float(flags_mod.flag("FLAGS_shed_wait_s"))
        self.clamp_tokens = int(
            flags_mod.flag("FLAGS_brownout_clamp_tokens"))
        self.brownout = BrownoutController() if brownout else None

    # -- scheduler hooks ---------------------------------------------------

    def observe_prefill(self, tokens, us):
        self.model.observe_prefill(tokens, us)

    def observe_decode(self, us):
        self.model.observe_decode(us)

    def estimate_tokens(self, sched, prompt):
        """Estimated tokens this prompt will actually COMPUTE at
        prefill — the prefix-cache plan's uncovered tail when caching
        is on (``plan_prefix`` is pure: no counters, no allocation), so
        a cache-hitting prompt predicts cheap, matching how admission
        will bill it."""
        if sched.prefix_cache:
            try:
                plan = sched.cache.plan_prefix(prompt)
                return max(len(prompt) - plan.covered_tokens, 1)
            except Exception:  # noqa: BLE001 — an estimate, never a failure
                pass
        return max(len(prompt), 1)

    def _queued_tokens(self, sched):
        return sum(r.est_tokens for r in sched.queue)

    def queue_retry_after(self, sched):
        """Predicted seconds until the current queue drains — the
        ``retry_after_s`` stamped on sheds and structured rejections.
        None until the model is primed (an unprimed estimate would be
        noise presented as advice)."""
        if not self.model.primed:
            return None
        wait_us, _ = self.model.predict(self._queued_tokens(sched),
                                        len(sched.queue), 0)
        return max(wait_us / 1e6, 0.001)

    def admit(self, sched, prompt, max_new_tokens, deadline, priority):
        """The submit-time gate. Returns ``(est_tokens,
        effective_max_new_tokens)`` or raises :class:`AdmissionRejected`
        (brownout priority floor, or a provably-unmeetable deadline).
        Prediction failures FAIL OPEN — a broken model must not refuse
        traffic the plain queue bound would have taken."""
        stage = self.brownout.stage if self.brownout is not None else 0
        if stage >= 1 and self.clamp_tokens \
                and max_new_tokens > self.clamp_tokens:
            max_new_tokens = self.clamp_tokens
            _m_clamped.inc()
        est = self.estimate_tokens(sched, prompt)
        wait_us = ttft_us = None
        if self.shedding:
            try:
                _faults.site("admission.predict")
                wait_us, ttft_us = self.model.predict(
                    self._queued_tokens(sched), len(sched.queue), est)
                _h_pred_ttft.observe(ttft_us)
            except Exception as e:  # noqa: BLE001 — fail open
                resilience.degrade("serving.admission", exc=e)
                wait_us = ttft_us = None
        floor = HIGH if stage >= 3 else (NORMAL if stage >= 2 else None)
        if floor is not None and priority > floor:
            _m_adm_rejected.inc()
            raise AdmissionRejected(
                f"serving.submit: brownout stage {stage} admits only "
                f"priority <= {floor} (got {priority})",
                reason="brownout", stage=stage, priority=priority,
                queue_depth=len(sched.queue),
                retry_after_s=None if wait_us is None
                else max(wait_us / 1e6, 0.001))
        if deadline is not None and ttft_us is not None \
                and self.model.primed:
            remaining = deadline.remaining()
            predicted_s = ttft_us / 1e6
            if predicted_s * self.optimism > remaining:
                _m_adm_rejected.inc()
                raise AdmissionRejected(
                    f"serving.submit: deadline provably unmeetable — "
                    f"predicted TTFT {predicted_s * 1e3:.1f}ms (even "
                    f"x{self.optimism} optimism) exceeds the "
                    f"{remaining * 1e3:.1f}ms remaining",
                    reason="deadline", predicted_ttft_s=predicted_s,
                    deadline_s=remaining, priority=priority,
                    queue_depth=len(sched.queue),
                    retry_after_s=max(wait_us / 1e6,
                                      predicted_s - remaining, 0.001))
        return est, max_new_tokens

    # -- the per-step control loop ----------------------------------------

    def pressure(self, sched):
        """Overload pressure in [0, inf): the max of the normalized
        watermark signals, gated on a real queued backlog
        (``FLAGS_shed_min_queue``) — pressure without demand is just a
        busy engine. >= 1.0 means shed territory."""
        q = len(sched.queue)
        if q < self.min_queue:
            return 0.0
        parts = [0.0]
        if sched.max_queue:
            parts.append(q / max(self.queue_frac * sched.max_queue, 1.0))
        # mesh-sliced caches: the KV watermark reads the BINDING slice
        # (the one the next admission would land on) — aggregate
        # headroom is a lie when the binding slice is full. Unsliced
        # caches return None -> the aggregate, byte-for-byte pre-mesh.
        occ = sched.cache.occupancy(slice=sched.cache.binding_slice())
        if occ["usable"]:
            parts.append((occ["active"] / occ["usable"]) / self.kv_frac)
        if self.model.primed:
            wait_us, _ = self.model.predict(self._queued_tokens(sched),
                                            q, 0)
            parts.append((wait_us / 1e6) / self.wait_s)
        return max(parts)

    def _shed_victim(self, queue):
        """Lowest-priority, newest queued request — never the top
        class (watermark shedding protects priority HIGH outright; only
        the brownout ladder's stage 3 can refuse everything else), and
        never a PREEMPTED request: it already streamed tokens to its
        caller (the SHED contract is "streamed nothing, retry safely"),
        and its device work is sunk cost worth finishing."""
        victim = None
        for r in queue:
            if r.priority <= HIGH or r.generated:
                continue
            if victim is None \
                    or (r.priority, r.rid) > (victim.priority, victim.rid):
                victim = r
        return victim

    def control(self, sched):
        """One per-step evaluation: compute pressure, walk the brownout
        ladder, shed queued requests while over pressure. Returns the
        pressure it acted on."""
        p = self.pressure(sched)
        if self.brownout is not None:
            self.brownout.update(p)
        if not self.shedding:
            return p
        while p >= 1.0 and sched.queue:
            victim = self._shed_victim(sched.queue)
            if victim is None:
                break
            sched.shed(victim,
                       retry_after_s=self.queue_retry_after(sched))
            p = self.pressure(sched)
        return p


class _NullOverload(OverloadController):
    """Disarmed control plane: every scheduler hook a no-op (the
    nearly-free-when-off contract — tools/overload_gate.py pins the
    byte-for-byte revert and counter silence)."""

    armed = False
    shedding = False
    brownout = None

    def __init__(self):  # no flag reads, no model
        pass

    def observe_prefill(self, tokens, us):
        pass

    def observe_decode(self, us):
        pass

    def admit(self, sched, prompt, max_new_tokens, deadline, priority):
        return 0, max_new_tokens

    def control(self, sched):
        return 0.0

    def queue_retry_after(self, sched):
        return None


NULL = _NullOverload()
