"""Self-speculative draft proposal: prompt-lookup (n-gram) decoding.

The cheapest useful draft model is the request's own context: natural
and code text repeat themselves (boilerplate, quoted spans, loops), and
greedy LLM continuations degenerate into repetition outright — so "find
the most recent earlier occurrence of the trailing n-gram and propose
what followed it" predicts the model's own next tokens far more often
than chance, for zero extra parameters and zero device work. This is
the prompt-lookup / n-gram speculation family (PLD, vLLM's
`speculative_model="[ngram]"`), chosen here over a learned draft model
so the tier-1 CPU path can run it and no second set of weights needs
loading, sharding, or versioning.

The scheduler (``Scheduler._decode_spec``) calls :func:`propose_draft`
per running request, verifies all drafts in ONE batched multi-position
paged sweep (``Llama.paged_spec_step``), accepts the longest
greedy-matching prefix, and rolls rejected rows back — greedy outputs
stay bit-identical to non-speculative decode because every accepted
token IS the model's own argmax (``tools/spec_gate.py`` pins it).
Proposal cost is pure host-side numpy on a context that is at most
``max_seq_len`` long.

Flags: ``FLAGS_serving_spec`` (master, default off),
``FLAGS_serving_spec_tokens`` (k), ``FLAGS_serving_spec_ngram``
(longest match tried). See docs/SERVING.md "Decode speed tiers".
"""

from __future__ import annotations

import numpy as np

__all__ = ["propose_draft", "REPETITIVE_CORPUS", "repetitive_prompts"]

# The high-acceptance evaluation corpus shared by tools/spec_gate.py,
# bench.py's decode_tiers rung, and examples/serve_llm.py --spec:
# (seed, size) pairs whose greedy continuation (for the seed-0 tiny
# model) is self-repetitive, so prompt-lookup drafts keep matching.
# Found empirically; deterministic (greedy decode is a pure function
# of weights + prompt). Retune HERE if the tiny model or its seed
# changes — the consumers all import it, so the gate floor, the
# decode_tiers ledger rung, and the demo stay comparable.
REPETITIVE_CORPUS = ((9, 9), (12, 9), (12, 12), (14, 6))


def repetitive_prompts():
    """Materialise :data:`REPETITIVE_CORPUS` as int prompt arrays."""
    return [np.random.default_rng(seed).integers(3, 250, size=size)
            for seed, size in REPETITIVE_CORPUS]


def propose_draft(context, max_tokens, ngram_max=3):
    """Propose up to ``max_tokens`` draft tokens continuing ``context``
    (1-D int array: prompt + everything generated so far).

    Tries the trailing ``n``-gram for ``n = ngram_max .. 1``: the MOST
    RECENT prior occurrence wins (recency tracks the current phrase
    better than frequency), and the tokens that followed it become the
    draft. Returns an int64 array, possibly empty (no repetition to
    exploit — the scheduler then falls back to plain decode for slots
    with nothing to verify). Pure and deterministic."""
    ids = np.ascontiguousarray(np.asarray(context).reshape(-1),
                               dtype=np.int64)
    n = int(ids.size)
    if n < 2 or max_tokens <= 0:
        return np.empty((0,), np.int64)
    for g in range(min(int(ngram_max), n - 1), 0, -1):
        tail = ids[n - g:]
        windows = np.lib.stride_tricks.sliding_window_view(ids, g)
        matches = np.flatnonzero((windows == tail).all(axis=1))
        # the last window IS the tail; only strictly-prior occurrences
        # have a continuation to steal
        matches = matches[matches < n - g]
        if matches.size:
            j = int(matches[-1])
            cont = ids[j + g:j + g + int(max_tokens)]
            if cont.size:
                return cont.copy()
    return np.empty((0,), np.int64)
