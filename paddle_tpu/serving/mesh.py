"""Mesh-sharded serving: the ``(data, model)`` device mesh behind
``FLAGS_serving_mesh``.

Everything serving-side up to PR 14 ran on exactly one chip. This
module points the training-side mesh machinery (``distributed/mesh``,
``jax.sharding``) at inference:

- the **model axis** tensor-parallels the served Llama: attention
  q/k/v projections and MLP gate/up shard their OUTPUT dim
  (column-parallel — heads split contiguously across shards), o/down
  shard their INPUT dim (row-parallel — XLA inserts the psum at the
  projection boundary), and the paged KV block pools shard by
  **kv-head** along the same axis, so the attention gather + einsum is
  embarrassingly parallel over heads (no collective inside attention;
  the all_gather/psum_scatter pair lives at the projection
  boundaries). Where the runtime jax exposes stable ``jax.shard_map``
  (``distributed.capability.has_jax_shard_map``) the decode attention
  runs under an explicit shard_map so each shard routes its local pool
  through ``kernels/pallas/paged_attention.py``; everywhere else the
  same sharding is expressed through ``NamedSharding`` on the program
  inputs and GSPMD propagation — numerically the same partitioning,
  chosen by the compiler.
- the **data axis** partitions the scheduler's capacity into
  *slices*: decode slots and pool blocks are divided across
  ``data`` slices, new requests bind to the least-loaded slice, and
  ``PagedKVCache.occupancy()`` / the admission+shed watermarks report
  and read per-slice (the foundation for disaggregated
  prefill/decode and per-slice routing later).

Host-side block tables, refcounts, prefix-cache digests, COW and LRU
eviction are **untouched**: tables stay replicated numpy, so every
shard sees the same block ids and the sharded gather is just the
single-device gather on a narrower head axis. Greedy outputs are
bit-identical to the 1-device run wherever XLA reduction order allows
(tools/mesh_gate.py pins the corpus), and ``FLAGS_serving_mesh`` unset
/ ``1x1`` is byte-for-byte pre-mesh behavior with ``serving.mesh.*``
counter silence.
"""

from __future__ import annotations

import numpy as np

from ..core import flags as flags_mod
from ..distributed.mesh import MeshAxisError, validate_mesh_axes
from ..profiler import metrics as _metrics

__all__ = ["ServingMesh", "parse_mesh_spec", "resolve_serving_mesh",
           "MeshAxisError"]

# armed-only telemetry: all silent while FLAGS_serving_mesh is unset
# (tools/mesh_gate.py pins the silence)
_g_devices = _metrics.gauge("serving.mesh.devices")
_g_data = _metrics.gauge("serving.mesh.data_slices")
_g_model = _metrics.gauge("serving.mesh.model_shards")
_c_engines = _metrics.counter("serving.mesh.engines")

# param-name suffix -> partition kind along the model axis (the
# Megatron split Llama.tp_placement_rules documents for training,
# applied to the serving replica): column-parallel shards [in, out] on
# out, row-parallel on in; everything else (embeddings, norms, lm_head)
# stays replicated so vocab argmax needs no cross-shard reduction.
_COL_SUFFIXES = ("q_proj.weight", "k_proj.weight", "v_proj.weight",
                 "gate_proj.weight", "up_proj.weight")
_ROW_SUFFIXES = ("o_proj.weight", "down_proj.weight")


def parse_mesh_spec(spec):
    """``'DATAxMODEL'`` -> ``(data, model)`` ints. ``''``/``None``/
    falsy strings parse to ``(1, 1)`` (disarmed). Raises ValueError on
    anything else malformed."""
    s = str(spec or "").strip().lower()
    if s in ("", "0", "off", "none", "false"):
        return (1, 1)
    parts = s.split("x")
    if len(parts) != 2:
        raise ValueError(
            f"FLAGS_serving_mesh: expected 'DATAxMODEL' (e.g. '1x8'), "
            f"got {spec!r}")
    try:
        d, m = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"FLAGS_serving_mesh: non-integer axis in {spec!r}") from None
    if d < 1 or m < 1:
        raise ValueError(
            f"FLAGS_serving_mesh: axis sizes must be >= 1, got {spec!r}")
    return (d, m)


class ServingMesh:
    """One serving engine's ``(data, model)`` mesh + its sharding
    vocabulary. Construction validates the axes against the visible
    device count (``distributed.mesh.validate_mesh_axes`` — a
    structured :class:`MeshAxisError` naming the axis, never a deep
    jax failure)."""

    AXES = ("data", "model")

    def __init__(self, data, model):
        import jax
        from jax.sharding import Mesh

        self.data = int(data)
        self.model = int(model)
        validate_mesh_axes((self.data, self.model), self.AXES)
        n = self.data * self.model
        devices = np.array(jax.devices()[:n], dtype=object).reshape(
            self.data, self.model)
        self.jax_mesh = Mesh(devices, axis_names=self.AXES)
        self._shard_map = None  # capability probe, memoized

    # -- identity ------------------------------------------------------

    @property
    def spec(self):
        return f"{self.data}x{self.model}"

    @property
    def devices(self):
        return self.data * self.model

    @property
    def trivial(self):
        return self.devices == 1

    def __repr__(self):
        return f"ServingMesh({self.spec})"

    # -- sharding vocabulary -------------------------------------------

    def sharding(self, *parts):
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self.jax_mesh, PartitionSpec(*parts))

    @property
    def replicated(self):
        return self.sharding()

    def param_sharding(self, name):
        """NamedSharding for one model parameter by its qualified name
        (the ``named_parameters`` path): attention/MLP projections
        shard along ``model``, everything else replicates."""
        if name.endswith(_COL_SUFFIXES):
            return self.sharding(None, "model")
        if name.endswith(_ROW_SUFFIXES):
            return self.sharding("model", None)
        return self.replicated

    def kv_pool_sharding(self):
        """[num_blocks, block_size, Hk, D] pools shard by kv-head."""
        return self.sharding(None, None, "model", None)

    def kv_scale_sharding(self):
        """[num_blocks, block_size, Hk] int8 scale rows follow the
        pools' kv-head split."""
        return self.sharding(None, None, "model")

    # -- model compatibility -------------------------------------------

    def validate_model(self, config):
        """The model axis must divide every dim it splits: q heads,
        kv heads, and the MLP hidden dim. Raises :class:`MeshAxisError`
        naming the axis and the offending extent."""
        m = self.model
        if m == 1:
            return
        for what, extent in (("num_heads", config.num_heads),
                             ("num_kv_heads", config.num_kv_heads),
                             ("intermediate_size",
                              config.intermediate_size)):
            if extent % m != 0:
                raise MeshAxisError(
                    f"serving mesh model axis {m} does not divide "
                    f"{what}={extent} — choose a model axis that "
                    f"divides the head and hidden extents",
                    axis="model", size=m, device_count=self.devices)

    # -- shard_map capability ------------------------------------------

    @property
    def shard_map_armed(self):
        """True when the decode attention should run under an explicit
        ``jax.shard_map`` (stable entry point present AND the model
        axis actually splits anything). Where absent, the same layout
        rides NamedSharding inputs + GSPMD propagation — the graceful
        gate for runtimes whose jax lacks shard_map."""
        if self._shard_map is None:
            from ..distributed import capability
            self._shard_map = (self.model > 1
                               and capability.has_jax_shard_map())
        return self._shard_map


def resolve_serving_mesh(mesh=None):
    """Resolve a Scheduler's ``mesh`` ctor kwarg (the
    ``FLAGS_serving_prefix_cache`` read-once-at-construction
    convention): ``None`` reads ``FLAGS_serving_mesh``; a string
    parses as ``'DATAxMODEL'``; a :class:`ServingMesh` passes through.
    Returns ``None`` for the trivial ``1x1`` mesh — the disarmed,
    byte-for-byte pre-mesh path."""
    if mesh is None:
        mesh = str(flags_mod.flag("FLAGS_serving_mesh"))
    if isinstance(mesh, ServingMesh):
        return None if mesh.trivial else mesh
    d, m = parse_mesh_spec(mesh)
    if (d, m) == (1, 1):
        return None
    return ServingMesh(d, m)


def note_engine(mesh):
    """Armed-engine telemetry (Scheduler construction): mesh-shape
    gauges + the engines counter. Never called disarmed — the
    counter-silence contract."""
    _g_devices.set(mesh.devices)
    _g_data.set(mesh.data)
    _g_model.set(mesh.model)
    _c_engines.inc()
