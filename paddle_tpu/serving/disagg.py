"""Disaggregated prefill/decode serving: the two-stage pipeline.

Prefill is compute-bound and decode is memory-bound; co-locating them
on every replica wastes both. This module splits them: a
:class:`DisaggPipeline` sits on top of the multi-replica
:class:`~paddle_tpu.serving.router.Router` and serves each request in
two stages over role-specialized replicas —

1. **prefill stage** — the stage-aware candidate sweep
   (``Router.stage_candidates("prefill")``: same-role + ``mixed``
   replicas, ranked health-over-load) picks a prefill replica, which
   runs ONLY the bucket ladder (``submit(prefill_only=True)``): the
   request finishes ``DONE`` at its first token with the prompt's KV
   blocks registered in the prefix cache, ready for export;
2. **transfer** — ``kv_transfer.export_prefix`` serializes exactly
   those blocks (int8 data + scale rows together on quantized pools)
   into a crc-framed payload, which streams to the decode replica
   through a :class:`LocalTransport` (in-process pools) or
   :class:`RpcTransport` (the distributed/rpc.py channel) under the
   ``disagg.transfer`` retry policy and fault site;
3. **decode stage** — ``kv_transfer.import_prefix`` lands the blocks
   into the decode replica's pool and registers the same digests, and
   ``submit_handoff`` admits the request straight into the batched
   decode step: ``plan_prefix`` reports full coverage,
   ``alloc_slot_cached`` maps the imported blocks, and ZERO prefill
   compute runs on the decode replica. The returned handle streams the
   FULL sequence (the prefill-sampled first token re-emits through
   it), so callers cannot tell the stages apart from co-located
   serving — greedy outputs are bit-identical (tools/disagg_gate.py
   pins it, fp32 and int8 pools).

**Fail-open ladder** — a broken fabric must never lose a request. Any
failure past the prefill stage (export refused, transfer fault, import
rejected, decode-side admission refused, or simply no decode-stage
candidate) degrades to CO-LOCATED serving on the prefill replica: its
prefix cache still holds the prompt's blocks, so the fallback submit
re-plans to full coverage and pays no extra prefill compute. Counted
``serving.disagg.fallbacks``, degraded + flight-recorded
(``resilience.degrade("disagg.fallback")``). Only when the fallback
ALSO refuses does :class:`~.router.NoReplicaAvailable` propagate —
carrying stage-keyed reasons (``no-prefill-replica`` /
``no-decode-replica`` / ``transfer-failed``) next to the per-replica
ones, with the smallest ``retry_after_s`` any structured rejection
suggested.

**Tracing** — the prefill request's ``serving.request`` root trace is
the request's ONE trace: the transfer records a ``serving.transfer``
child span (bytes, blocks, destination replica) and the decode stage
opens a ``serving.decode_stage`` child on the SAME trace via the
picklable span context (``trace_parent``), so route -> prefill ->
transfer -> decode -> terminal reads as one cross-replica trace.
``CostReport`` bills each stage to the replica that did the work: the
prefill replica carries queue + prefill time, the decode replica
carries decode time plus the informational ``transfer_us`` /
``transfer_bytes`` axes.

``FLAGS_serving_disagg=0`` (read at construction, the
``FLAGS_serving_router`` convention) makes the pipeline a byte-for-byte
pass-through to ``Router.submit`` — identical handles, zero
``serving.disagg.*`` counter movement (tools/disagg_gate.py pins the
silence).
"""

from __future__ import annotations

import time

from ..core import flags as flags_mod
from ..core import resilience
from ..profiler import metrics as _metrics
from ..profiler import tracing as _tracing
from ..testing import faults as _faults
from . import kv_transfer
from .kv_transfer import TransferError
from .router import NoReplicaAvailable
from .scheduler import HandoffError, QueueFullError
from .frontend import NotReadyError

__all__ = ["DisaggPipeline", "LocalTransport", "RpcTransport",
           "register_rpc_engine"]

_c_handoffs = _metrics.counter("serving.disagg.handoffs")
_c_transfer_bytes = _metrics.counter("serving.disagg.transfer_bytes")
_c_transfer_us = _metrics.counter("serving.disagg.transfer_us")
_c_fallbacks = _metrics.counter("serving.disagg.fallbacks")
# degenerate topology: prefill and decode candidates are the SAME single
# replica — a two-stage attempt would export/import a prefix into the
# pool it came from and then "fall back" on the guaranteed self-handoff
# refusal. Counted here (not in fallbacks: nothing failed) and served
# co-located directly.
_c_colocated = _metrics.counter("serving.disagg.colocated")


class LocalTransport:
    """In-process fabric: the frame lands straight into the decode
    replica's pool. The topology every test/gate in this repo runs —
    and the semantics :class:`RpcTransport` must match, since the frame
    bytes are identical either way."""

    def send(self, replica, frame):
        if replica.engine is None:
            raise TransferError(
                f"transport: replica {replica.replica_id} has no "
                f"engine to import into")
        return kv_transfer.import_prefix(replica.engine.cache, frame)


# rpc-visible import targets: an engine must be registered here (by the
# process that owns it) before an RpcTransport can land frames into it
_RPC_ENGINES = {}


def register_rpc_engine(name, engine):
    """Expose ``engine``'s pool as an rpc import target under ``name``
    (conventionally its replica_id). The decode-side process calls this
    once; ``_rpc_import`` resolves the name inside the rpc handler."""
    _RPC_ENGINES[str(name)] = engine
    return engine


def _rpc_import(name, frame):
    """Remote half of :class:`RpcTransport` — runs on the decode host
    via ``distributed.rpc``. Loud KeyError on an unregistered target
    (the caller's retry/fallback ladder handles it)."""
    eng = _RPC_ENGINES.get(str(name))
    if eng is None:
        raise TransferError(
            f"rpc import: no engine registered as {name!r} "
            f"(call disagg.register_rpc_engine on the decode host)")
    return kv_transfer.import_prefix(eng.cache, frame)


class RpcTransport:
    """Cross-host fabric: the frame ships over the distributed/rpc.py
    channel (PR 4/6 — length-prefixed, crc-checked, trace-stitched) to
    ``_rpc_import`` on the worker that owns the decode replica.
    ``worker_of`` maps a replica_id to its rpc worker name (default:
    the replica_id IS the worker name). Admission itself still needs an
    engine-bound replica record (cross-host submit rides the rpc layer
    — ROADMAP); this transport is the block-streaming half."""

    def __init__(self, worker_of=None, timeout=60.0):
        self._worker_of = worker_of or (lambda rid: rid)
        self.timeout = float(timeout)

    def send(self, replica, frame):
        from ..distributed import rpc as _rpc
        return _rpc.rpc_sync(
            self._worker_of(replica.replica_id), _rpc_import,
            args=(replica.replica_id, bytes(frame)),
            timeout=self.timeout)


class DisaggPipeline:
    """See module docstring. Construct once per front door, over a
    :class:`~.router.Router` whose replicas carry roles
    (``add_replica(..., role=...)`` or the fleet registry ``role``
    field). ``transport`` defaults to :class:`LocalTransport`;
    ``prefill_timeout_s`` bounds the wait for the prefill stage's
    first token."""

    def __init__(self, router, transport=None, prefill_timeout_s=120.0):
        self._armed = bool(flags_mod.flag("FLAGS_serving_disagg"))
        self.router = router
        self.transport = transport if transport is not None \
            else LocalTransport()
        self.prefill_timeout_s = float(prefill_timeout_s)

    # -- stepping (foreground engines: tests/gates) ---------------------

    def run_until_idle(self):
        """Drive every foreground (``background=False``) engine-bound
        replica until idle — the deterministic stepping helper gates
        use. Background engines drive themselves."""
        while True:
            busy = False
            for v in self.router.view():
                rep = self.router._replicas.get(v["replica_id"])
                if rep is None or rep.engine is None:
                    continue
                eng = rep.engine
                if not eng._background and eng.has_work:
                    eng.run_until_idle()
                    busy = True
            if not busy:
                return

    # -- submission -----------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens=32, *, deadline_s=None,
               deadline=None, priority=None, on_token=None):
        """Serve one request disaggregated; returns the decode-stage
        handle (or, on fallback, the co-located handle — callers see
        one handle streaming the full sequence either way). Disarmed,
        a byte-for-byte ``Router.submit`` pass-through."""
        if not self._armed:
            return self.router.submit(
                prompt_ids, max_new_tokens, deadline_s=deadline_s,
                deadline=deadline, priority=priority, on_token=on_token)
        if deadline is None and deadline_s is not None:
            deadline = resilience.Deadline.after(deadline_s)

        # -- stage 1: prefill ------------------------------------------
        reasons = {}
        retry_after = None
        cands = self.router.stage_candidates("prefill", reasons=reasons)
        if not cands:
            reasons["no-prefill-replica"] = \
                "no READY prefill-stage candidate"
            raise NoReplicaAvailable(
                "disagg: prefill stage starved", reasons=reasons,
                retry_after_s=retry_after)
        # co-located short-circuit: when the prefill and decode stages
        # resolve to the SAME single replica (one mixed-role replica —
        # common in shakedown topologies), a two-stage attempt can only
        # self-handoff and land in the fallback path. Serve it directly
        # instead: not a failure, so it counts colocated, not fallbacks.
        dprobe = self.router.stage_candidates("decode")
        if dprobe and \
                {r.replica_id for r in cands} == \
                {r.replica_id for r in dprobe} and len(
                    {r.replica_id for r in cands}) == 1:
            _c_colocated.inc()
            return self.router.submit(
                prompt_ids, max_new_tokens, deadline=deadline,
                priority=priority, on_token=on_token)
        prefill_rep = None
        phandle = None
        for rep in cands:
            try:
                _faults.site("disagg.prefill")
                phandle = rep.engine.submit(
                    prompt_ids, max_new_tokens, deadline=deadline,
                    priority=priority, prefill_only=True)
                prefill_rep = rep
                break
            except (NotReadyError, QueueFullError, RuntimeError) as e:
                reasons[rep.replica_id] = type(e).__name__
                ra = getattr(e, "retry_after_s", None)
                if ra is not None:
                    retry_after = ra if retry_after is None \
                        else min(retry_after, ra)
        if prefill_rep is None:
            reasons["no-prefill-replica"] = \
                f"all {len(cands)} prefill candidate(s) refused"
            raise NoReplicaAvailable(
                "disagg: prefill stage starved", reasons=reasons,
                retry_after_s=retry_after)
        if not prefill_rep.engine._background:
            prefill_rep.engine.run_until_idle()
        toks = phandle.result(timeout=self.prefill_timeout_s)
        preq = phandle._req
        if not toks:
            # the prefill stage terminated without a first token
            # (cancelled / timed out / shed): nothing to hand off and
            # nothing to fall back to — surface the terminal handle
            return phandle
        first_token = toks[0]
        root = preq.span
        ctx = root.context() if root.recording else None

        # -- stage 2: transfer + decode admission ----------------------
        err = None
        try:
            t0 = time.perf_counter_ns()
            frame, exported = kv_transfer.export_prefix(
                prefill_rep.engine.cache, prompt_ids)
            dec_reasons = {}
            dcands = self.router.stage_candidates(
                "decode", exclude={prefill_rep.replica_id},
                reasons=dec_reasons)
            if not dcands:
                reasons.update(dec_reasons)
                reasons["no-decode-replica"] = \
                    "no READY decode-stage candidate"
                raise TransferError("disagg: decode stage starved")
            pol = resilience.policy("disagg.transfer", max_attempts=2,
                                    retry_on=(TransferError,
                                              ConnectionError,
                                              TimeoutError))
            for rep in dcands:
                try:
                    def _send(rep=rep):
                        _faults.site("disagg.transfer")
                        return self.transport.send(rep, frame)
                    imported = resilience.retry_call(_send, policy=pol)
                    handle = rep.engine.submit_handoff(
                        prompt_ids, first_token, max_new_tokens,
                        deadline=deadline, priority=priority,
                        on_token=on_token, trace_parent=ctx,
                        transfer_us=(time.perf_counter_ns() - t0)
                        / 1000.0,
                        transfer_bytes=exported.nbytes)
                except (TransferError, HandoffError, NotReadyError,
                        QueueFullError, ConnectionError, TimeoutError,
                        RuntimeError) as e:
                    reasons[rep.replica_id] = type(e).__name__
                    err = e
                    continue
                dur_us = (time.perf_counter_ns() - t0) / 1000.0
                _c_handoffs.inc()
                _c_transfer_bytes.inc(exported.nbytes)
                _c_transfer_us.inc(dur_us)
                _tracing.record_span(
                    "serving.transfer", root, dur_us,
                    nbytes=exported.nbytes, blocks=exported.blocks,
                    src=prefill_rep.replica_id, dst=rep.replica_id)
                return handle
            reasons["transfer-failed"] = \
                f"all {len(dcands)} decode candidate(s) refused " \
                f"({type(err).__name__ if err else 'unknown'})"
            raise err if err is not None else TransferError(
                "disagg: transfer failed")
        except (TransferError, HandoffError, NotReadyError,
                QueueFullError, ConnectionError, TimeoutError,
                RuntimeError) as e:
            # -- fail open: co-located serving on the prefill replica.
            # Its prefix cache still holds the prompt's blocks, so the
            # fallback re-plans to full coverage — no re-prefill, no
            # lost request, a broken fabric degrades instead of failing
            _c_fallbacks.inc()
            resilience.degrade(
                "disagg.fallback",
                detail=f"prefill={prefill_rep.replica_id} "
                       f"rid={preq.rid}", exc=e)
            try:
                return prefill_rep.engine.submit(
                    prompt_ids, max_new_tokens, deadline=deadline,
                    priority=priority, on_token=on_token)
            except (NotReadyError, QueueFullError, RuntimeError) as fe:
                reasons[prefill_rep.replica_id] = type(fe).__name__
                reasons.setdefault("transfer-failed",
                                   type(e).__name__)
                ra = getattr(fe, "retry_after_s", None)
                if ra is not None:
                    retry_after = ra if retry_after is None \
                        else min(retry_after, ra)
                raise NoReplicaAvailable(
                    "disagg: transfer failed and co-located fallback "
                    "refused", reasons=reasons,
                    retry_after_s=retry_after) from fe
