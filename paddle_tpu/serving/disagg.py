"""Disaggregated prefill/decode serving: the two-stage pipeline.

Prefill is compute-bound and decode is memory-bound; co-locating them
on every replica wastes both. This module splits them: a
:class:`DisaggPipeline` sits on top of the multi-replica
:class:`~paddle_tpu.serving.router.Router` and serves each request in
two stages over role-specialized replicas —

1. **prefill stage** — the stage-aware candidate sweep
   (``Router.stage_candidates("prefill")``: same-role + ``mixed``
   replicas, ranked health-over-load) picks a prefill replica, which
   runs ONLY the bucket ladder (``submit(prefill_only=True)``): the
   request finishes ``DONE`` at its first token with the prompt's KV
   blocks registered in the prefix cache, ready for export;
2. **transfer** — ``kv_transfer.export_prefix`` serializes exactly
   those blocks (int8 data + scale rows together on quantized pools)
   into a crc-framed payload, which streams to the decode replica
   through a :class:`LocalTransport` (in-process pools) or
   :class:`RpcTransport` (the distributed/rpc.py channel) under the
   ``disagg.transfer`` retry policy and fault site;
3. **decode stage** — ``kv_transfer.import_prefix`` lands the blocks
   into the decode replica's pool and registers the same digests, and
   ``submit_handoff`` admits the request straight into the batched
   decode step: ``plan_prefix`` reports full coverage,
   ``alloc_slot_cached`` maps the imported blocks, and ZERO prefill
   compute runs on the decode replica. The returned handle streams the
   FULL sequence (the prefill-sampled first token re-emits through
   it), so callers cannot tell the stages apart from co-located
   serving — greedy outputs are bit-identical (tools/disagg_gate.py
   pins it, fp32 and int8 pools).

**Cross-host decode** — the decode stage can live in ANOTHER process.
A decode host registers its engine with :func:`register_rpc_engine`;
an engine-less router replica (registry- or url-discovered) then
qualifies as a decode candidate when the transport can admit remotely
(:class:`RpcTransport`), and the whole stage rides rpc: ``_rpc_admit``
imports the frame AND admits the request in one idempotent call (keyed
on ``(request_id, frame digest)`` — a retried admission after an
ambiguous timeout dedups instead of double-allocating, counted
``serving.disagg.dup_admits``), and a pull-based token relay
(``_rpc_pull``) streams tokens back against a MONOTONE CURSOR: the
caller's :class:`RemoteHandoffHandle` pulls from ``len(delivered)``,
so every position reaches the caller's sinks exactly once no matter
how the channel flaps (the PR 12 ``RoutedHandle`` discipline applied
cross-host). Ownership is explicit: each remote handoff holds a TTL'd
:class:`~paddle_tpu.core.resilience.Lease` on BOTH sides — the caller
renews on successful pulls and on a fresh decode fleet heartbeat; the
decode host renews on every pull that lands. Expiry before a terminal
status means the peer is presumed dead: the caller reclaims ownership
and fails open to co-located decode replaying from the cursor
(``serving.disagg.lease_expired`` + ``reclaims``); the decode host
cancels the orphan and sweeps its imported refcount-0 blocks back to
the free list (``serving.disagg.orphan_blocks``). A decode host that
RESTARTS mid-lease has no admission record and refuses the stale
cursor loudly (:class:`~.kv_transfer.RelayError`,
``serving.disagg.stale_cursors``) — reclaim, never resync.

**Fail-open ladder** — a broken fabric must never lose a request. Any
failure past the prefill stage (export refused, transfer fault, import
rejected, decode-side admission refused, or simply no decode-stage
candidate) degrades to CO-LOCATED serving on the prefill replica: its
prefix cache still holds the prompt's blocks, so the fallback submit
re-plans to full coverage and pays no extra prefill compute. Counted
``serving.disagg.fallbacks``, degraded + flight-recorded
(``resilience.degrade("disagg.fallback")``). Only when the fallback
ALSO refuses does :class:`~.router.NoReplicaAvailable` propagate —
carrying stage-keyed reasons (``no-prefill-replica`` /
``no-decode-replica`` / ``transfer-failed``) next to the per-replica
ones, with the smallest ``retry_after_s`` any structured rejection
suggested. Post-admission remote death is the reclaim rung above —
counted ``serving.disagg.reclaims``, NOT ``fallbacks`` (the handoff
happened; arrivals == handoffs + fallbacks + colocated still holds).

**Tracing** — the prefill request's ``serving.request`` root trace is
the request's ONE trace: the transfer records a ``serving.transfer``
child span (bytes, blocks, destination replica) and the decode stage
opens a ``serving.decode_stage`` child on the SAME trace via the
picklable span context (``trace_parent``), so route -> prefill ->
transfer -> decode -> terminal reads as one cross-replica trace.
``CostReport`` bills each stage to the replica that did the work: the
prefill replica carries queue + prefill time, the decode replica
carries decode time plus the informational ``transfer_us`` /
``transfer_bytes`` axes.

``FLAGS_serving_disagg=0`` (read at construction, the
``FLAGS_serving_router`` convention) makes the pipeline a byte-for-byte
pass-through to ``Router.submit`` — identical handles, zero
``serving.disagg.*`` counter movement (tools/disagg_gate.py pins the
silence).
"""

from __future__ import annotations

import hashlib
import pickle
import threading
import time

from ..core import flags as flags_mod
from ..core import resilience
from ..profiler import metrics as _metrics
from ..profiler import tracing as _tracing
from ..testing import faults as _faults
from . import kv_transfer
from .kv_transfer import RelayError, TransferError, TransferTimeout
from .router import NoReplicaAvailable
from .scheduler import HandoffError, QueueFullError, RequestStatus
from .frontend import NotReadyError

__all__ = ["DisaggPipeline", "LocalTransport", "RpcTransport",
           "RemoteHandoffHandle", "register_rpc_engine",
           "sweep_remote"]

_c_handoffs = _metrics.counter("serving.disagg.handoffs")
_c_transfer_bytes = _metrics.counter("serving.disagg.transfer_bytes")
_c_transfer_us = _metrics.counter("serving.disagg.transfer_us")
_c_fallbacks = _metrics.counter("serving.disagg.fallbacks")
# degenerate topology: prefill and decode candidates are the SAME single
# replica — a two-stage attempt would export/import a prefix into the
# pool it came from and then "fall back" on the guaranteed self-handoff
# refusal. Counted here (not in fallbacks: nothing failed) and served
# co-located directly.
_c_colocated = _metrics.counter("serving.disagg.colocated")
# -- remote (cross-process) handoff plane (module docstring) -------------
_c_remote = _metrics.counter("serving.disagg.remote_handoffs")
# a frame re-shipped after an AMBIGUOUS timeout (TransferTimeout: sent,
# delivery unknown). Safe — import dedups, admission is idempotent —
# but never silently merged: up-is-worse (tools/regression_gate.py)
_c_dup_frames = _metrics.counter("serving.disagg.dup_frames")
# a retried _rpc_admit that found its (request_id, digest) record
_c_dup_admits = _metrics.counter("serving.disagg.dup_admits")
_c_pulls = _metrics.counter("serving.disagg.relay_pulls")
# leases that ran out before a terminal status (either side counts its
# own view); up-is-worse — a healthy fleet renews faster than it expires
_c_lease_expired = _metrics.counter("serving.disagg.lease_expired")
# caller-side ownership reclaims (post-admission fail-open): every one
# completed co-located, replayed from the cursor
_c_reclaims = _metrics.counter("serving.disagg.reclaims")
# decode-side imported blocks swept back to the free list after their
# lease died (orphan reclamation)
_c_orphan_blocks = _metrics.counter("serving.disagg.orphan_blocks")
# pulls refused loudly: no admission record (restart/reclaim) or a
# cursor past the buffer — the caller must reclaim, never resync
_c_stale_cursors = _metrics.counter("serving.disagg.stale_cursors")


class LocalTransport:
    """In-process fabric: the frame lands straight into the decode
    replica's pool. The topology every test/gate in this repo runs —
    and the semantics :class:`RpcTransport` must match, since the frame
    bytes are identical either way."""

    def send(self, replica, frame):
        if replica.engine is None:
            raise TransferError(
                f"transport: replica {replica.replica_id} has no "
                f"engine to import into")
        return kv_transfer.import_prefix(replica.engine.cache, frame)


# rpc-visible import targets: an engine must be registered here (by the
# process that owns it) before an RpcTransport can land frames into it
_RPC_ENGINES = {}
# decode-side admission ledger: (engine name, request_id) -> record.
# The idempotency table AND the relay buffer AND the lease registry —
# one record per remote handoff, swept by sweep_remote()
_ADMISSIONS = {}
_ADMIT_LOCK = threading.Lock()


class _RemoteAdmission:
    """Decode-side state for one remote handoff: the dedup key, the
    live engine handle, the append-only token buffer the relay reads
    from, the decode-side lease (prefill liveness), and the import
    result (the exact blocks orphan reclamation may sweep)."""

    __slots__ = ("key", "frame_digest", "engine", "handle", "tokens",
                 "lease", "imported", "orphaned")

    def __init__(self, key, frame_digest, engine, lease, imported):
        self.key = key
        self.frame_digest = frame_digest
        self.engine = engine
        self.handle = None
        self.tokens = []
        self.lease = lease
        self.imported = imported
        self.orphaned = False


def register_rpc_engine(name, engine, registrar=None):
    """Expose ``engine`` as an rpc target under ``name``
    (conventionally its replica_id): frame imports (``_rpc_import``),
    remote admission (``_rpc_admit``), and the token relay
    (``_rpc_pull``) all resolve the name inside the rpc handler. The
    decode-side process calls this once. ``registrar`` (the replica's
    ``profiler.fleet.Registrar``) opts the handoff plane into the
    fleet heartbeat: lease state rides every member payload and
    :func:`sweep_remote` runs once per beat, so orphan reclamation
    does not depend on relay traffic arriving."""
    _RPC_ENGINES[str(name)] = engine
    if registrar is not None:
        # COMPOSE with the registrar's other payload contributors
        # (Registrar.add_extra) — clobbering extra_fn would silently
        # drop the geometry / digest advertisements sharing the beat
        registrar.add_extra(lambda: lease_payload(name))
        # decode hosts pre-register pool geometry so remote admission
        # and peer pulls refuse a mismatch BEFORE a frame ships
        # (kv_transfer.check_geometry against this payload)
        registrar.add_extra(
            lambda: {"kv_geom": kv_transfer.geometry(engine.cache)})
        registrar.add_beat_hook(lambda: sweep_remote(name))
    return engine


def lease_payload(name):
    """Lease state for ``name``'s member payload (fleet heartbeat):
    how many remote handoffs this decode host is serving and the
    tightest remaining TTL — the aggregator-visible half of the
    ownership protocol."""
    with _ADMIT_LOCK:
        recs = [r for (n, _), r in _ADMISSIONS.items() if n == name]
    live = [r for r in recs if not r.lease.expired()]
    p = {"leases": len(live)}
    if live:
        p["lease_min_remaining_s"] = round(
            min(r.lease.remaining() for r in live), 3)
    return p


def sweep_remote(name=None):
    """Decode-side orphan reclamation: for every admission whose lease
    EXPIRED — cancel it if still running (the prefill side went silent
    mid-stream: it has either died or already reclaimed ownership),
    and once terminal, sweep the blocks its import freshly allocated
    back to the truly-free list (``kv_transfer.release_import``) and
    drop the record. A record that finished normally (lease simply
    aged out after the caller pulled the terminal status) is dropped
    WITHOUT releasing blocks — they are legitimate parked prefix-cache
    entries. Runs on every rpc touch of the handoff plane plus once
    per fleet heartbeat (:func:`register_rpc_engine`); returns the
    number of blocks reclaimed."""
    reclaimed = 0
    with _ADMIT_LOCK:
        items = list(_ADMISSIONS.items())
    for key, rec in items:
        if name is not None and key[0] != str(name):
            continue
        if not rec.lease.expired():
            continue
        status = rec.handle.status
        if status not in RequestStatus.TERMINAL:
            if not rec.orphaned:
                rec.orphaned = True
                _c_lease_expired.inc()
                resilience.degrade(
                    "disagg.lease",
                    detail=f"rid={key[1]} status={status} "
                           f"age={rec.lease.age():.3f}s")
                rec.handle.cancel()
            # blocks free at the next step boundary; a later sweep
            # (next beat / next rpc) finishes the reclaim
            continue
        if rec.orphaned:
            n = kv_transfer.release_import(rec.engine.cache,
                                           rec.imported)
            _c_orphan_blocks.inc(n)
            reclaimed += n
        with _ADMIT_LOCK:
            _ADMISSIONS.pop(key, None)
    return reclaimed


def _rpc_import(name, frame):
    """Remote half of :meth:`RpcTransport.send` — runs on the decode
    host via ``distributed.rpc``. Loud on an unregistered target (the
    caller's retry/fallback ladder handles it)."""
    eng = _RPC_ENGINES.get(str(name))
    if eng is None:
        raise TransferError(
            f"rpc import: no engine registered as {name!r} "
            f"(call disagg.register_rpc_engine on the decode host)")
    return kv_transfer.import_prefix(eng.cache, frame)


def _rpc_admit(name, request_id, frame_digest, frame, prompt_ids,
               first_token, max_new_tokens=32, priority=None,
               deadline_s=None, trace_parent=None, transfer_us=0.0,
               transfer_bytes=0, lease_ttl_s=10.0):
    """Remote decode-stage admission — import + ``submit_handoff`` +
    lease grant in ONE rpc, idempotent on ``(request_id, frame
    digest)``: a retried call after an ambiguous timeout finds the
    record, renews the lease, and acks (``serving.disagg.dup_admits``)
    instead of double-allocating a slot; the SAME request_id under a
    DIFFERENT digest is refused loudly (two prefills claiming one
    identity is a bug, not a retry). If admission fails after the
    import landed, the freshly imported blocks are released before the
    error propagates — a refused handoff must not leave parked blocks
    behind (the co-located pipeline applies the same discipline)."""
    eng = _RPC_ENGINES.get(str(name))
    if eng is None:
        raise TransferError(
            f"rpc admit: no engine registered as {name!r} "
            f"(call disagg.register_rpc_engine on the decode host)")
    sweep_remote(name)
    key = (str(name), str(request_id))
    with _ADMIT_LOCK:
        rec = _ADMISSIONS.get(key)
        if rec is not None:
            if rec.frame_digest != frame_digest:
                raise TransferError(
                    f"rpc admit: request {request_id!r} already "
                    f"admitted under a different frame digest "
                    f"(have {rec.frame_digest[:8]}…, "
                    f"got {str(frame_digest)[:8]}…) — refusing")
            _c_dup_admits.inc()
            rec.lease.renew()
            _faults.site("disagg.admit.ack")
            return {"ok": True, "dedup": True}
        imported = kv_transfer.import_prefix(eng.cache, frame)
        rec = _RemoteAdmission(
            key, frame_digest, eng,
            lease=resilience.Lease(f"disagg/{request_id}",
                                   lease_ttl_s),
            imported=imported)
        try:
            rec.handle = eng.submit_handoff(
                prompt_ids, first_token, max_new_tokens,
                deadline_s=deadline_s, priority=priority,
                on_token=rec.tokens.append, trace_parent=trace_parent,
                transfer_us=transfer_us, transfer_bytes=transfer_bytes,
                handoff_id=str(request_id))
        except BaseException:
            kv_transfer.release_import(eng.cache, imported)
            raise
        _ADMISSIONS[key] = rec
    # the admitted-but-ack-lost window: an injection here simulates a
    # response that died on the wire AFTER the slot was allocated —
    # exactly what the idempotent retry above must absorb
    _faults.site("disagg.admit.ack")
    return {"ok": True, "dedup": False}


def _rpc_pull(name, request_id, cursor):
    """One relay round, decode side: renew the lease (the pull IS the
    prefill side's liveness signal), read status BEFORE tokens (a
    terminal status therefore implies the token list is complete), and
    return everything past the caller's monotone ``cursor``. A missing
    record (this host restarted mid-lease, or swept the admission as
    orphaned) or a cursor past the buffer refuses LOUDLY with
    :class:`~.kv_transfer.RelayError` — the caller must reclaim
    ownership, never quietly resync. Terminal responses carry the
    request's CostReport when it pickles."""
    sweep_remote(name)
    key = (str(name), str(request_id))
    with _ADMIT_LOCK:
        rec = _ADMISSIONS.get(key)
    if rec is None:
        _c_stale_cursors.inc()
        raise RelayError(
            f"relay: no admission record for {request_id!r} on "
            f"{name!r} — decode host restarted mid-lease or the lease "
            f"was reclaimed; stale cursor {cursor} refused")
    t0 = time.perf_counter_ns()
    rec.lease.renew()
    status = rec.handle.status
    toks = list(rec.tokens)
    cursor = int(cursor)
    if cursor > len(toks):
        _c_stale_cursors.inc()
        raise RelayError(
            f"relay: cursor {cursor} past the {len(toks)}-token "
            f"buffer for {request_id!r} — refusing")
    _c_pulls.inc()
    resp = {"tokens": toks[cursor:], "cursor": len(toks),
            "status": status}
    rec.engine.scheduler.accounting.note_relay(
        rec.handle._req, (time.perf_counter_ns() - t0) / 1000.0)
    if status in RequestStatus.TERMINAL:
        cost = rec.handle.cost()
        try:
            pickle.dumps(cost)
            resp["cost"] = cost
        except Exception:  # noqa: BLE001 — cost is advisory; the relay
            pass           # must deliver the terminal status regardless
    return resp


def _rpc_cancel(name, request_id):
    """Best-effort remote cancel: the caller walked away (explicit
    cancel, or ownership reclaim before fail-open). Expires the lease
    immediately and marks the record orphaned so the next sweep
    reclaims the imported blocks without waiting out the TTL. True iff
    a record existed."""
    key = (str(name), str(request_id))
    with _ADMIT_LOCK:
        rec = _ADMISSIONS.get(key)
    if rec is None:
        return False
    rec.handle.cancel()
    rec.lease.ttl_s = 0.0
    rec.orphaned = True
    sweep_remote(name)
    return True


class RpcTransport:
    """Cross-host fabric: frames AND admission AND the token relay
    ride the distributed/rpc.py channel (length-prefixed, crc-checked,
    trace-stitched) to the worker that owns the decode replica.
    ``worker_of`` maps a replica_id to its rpc worker name (default:
    the replica_id IS the worker name).

    Every call classifies channel death: a failure AFTER the call
    frame went out (``frame_sent`` — distributed/rpc.py annotates it)
    re-raises as :class:`~.kv_transfer.TransferTimeout`, the AMBIGUOUS
    case where the remote may have executed the call and only the ack
    died. The pipeline retries those (import dedups, admission is
    idempotent) but counts the re-shipped frame
    ``serving.disagg.dup_frames``. A refused dial stays a plain
    ``ConnectionError`` — nothing was sent, retry is free."""

    def __init__(self, worker_of=None, timeout=60.0):
        self._worker_of = worker_of or (lambda rid: rid)
        self.timeout = float(timeout)

    def _call(self, replica_id, fn, args=(), kwargs=None,
              timeout=None):
        from ..distributed import rpc as _rpc
        try:
            return _rpc.rpc_sync(
                self._worker_of(replica_id), fn, args=tuple(args),
                kwargs=kwargs or {},
                timeout=self.timeout if timeout is None
                else float(timeout))
        except (TimeoutError, OSError, EOFError) as e:
            if getattr(e, "frame_sent", False):
                raise TransferTimeout(
                    f"rpc {getattr(fn, '__name__', fn)} to "
                    f"{replica_id}: channel died after the frame was "
                    f"sent — delivery unknown ({type(e).__name__})"
                ) from e
            raise

    def send(self, replica, frame):
        return self._call(replica.replica_id, _rpc_import,
                          args=(replica.replica_id, bytes(frame)))

    def admit(self, replica, request):
        """Remote admission (``_rpc_admit`` kwargs ride verbatim)."""
        return self._call(replica.replica_id, _rpc_admit,
                          args=(replica.replica_id,), kwargs=request)

    def pull(self, replica, request_id, cursor, timeout=None):
        return self._call(replica.replica_id, _rpc_pull,
                          args=(replica.replica_id, str(request_id),
                                int(cursor)), timeout=timeout)

    def cancel(self, replica, request_id):
        return self._call(replica.replica_id, _rpc_cancel,
                          args=(replica.replica_id, str(request_id)))


class DisaggPipeline:
    """See module docstring. Construct once per front door, over a
    :class:`~.router.Router` whose replicas carry roles
    (``add_replica(..., role=...)`` or the fleet registry ``role``
    field). ``transport`` defaults to :class:`LocalTransport`;
    ``prefill_timeout_s`` bounds the wait for the prefill stage's
    first token. ``lease_ttl_s`` is the remote-handoff ownership TTL
    (both sides; module docstring) and ``relay_poll_s`` the idle-pull
    pause of the token relay — both only matter when the transport can
    admit remotely (:class:`RpcTransport`)."""

    def __init__(self, router, transport=None, prefill_timeout_s=120.0,
                 lease_ttl_s=10.0, relay_poll_s=0.01):
        self._armed = bool(flags_mod.flag("FLAGS_serving_disagg"))
        self.router = router
        self.transport = transport if transport is not None \
            else LocalTransport()
        self.prefill_timeout_s = float(prefill_timeout_s)
        self.lease_ttl_s = float(lease_ttl_s)
        self.relay_poll_s = float(relay_poll_s)
        # remote admission needs a transport that carries it; the
        # in-process LocalTransport never routes to engine-less replicas
        self._remote_ok = hasattr(self.transport, "admit")

    # -- stepping (foreground engines: tests/gates) ---------------------

    def run_until_idle(self):
        """Drive every foreground (``background=False``) engine-bound
        replica until idle — the deterministic stepping helper gates
        use. Background engines drive themselves."""
        while True:
            busy = False
            for v in self.router.view():
                rep = self.router._replicas.get(v["replica_id"])
                if rep is None or rep.engine is None:
                    continue
                eng = rep.engine
                if not eng._background and eng.has_work:
                    eng.run_until_idle()
                    busy = True
            if not busy:
                return

    # -- submission -----------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens=32, *, deadline_s=None,
               deadline=None, priority=None, on_token=None):
        """Serve one request disaggregated; returns the decode-stage
        handle (or, on fallback, the co-located handle — callers see
        one handle streaming the full sequence either way). Disarmed,
        a byte-for-byte ``Router.submit`` pass-through."""
        if not self._armed:
            return self.router.submit(
                prompt_ids, max_new_tokens, deadline_s=deadline_s,
                deadline=deadline, priority=priority, on_token=on_token)
        if deadline is None and deadline_s is not None:
            deadline = resilience.Deadline.after(deadline_s)

        # -- stage 1: prefill ------------------------------------------
        reasons = {}
        retry_after = None
        cands = self.router.stage_candidates("prefill", reasons=reasons)
        if not cands:
            reasons["no-prefill-replica"] = \
                "no READY prefill-stage candidate"
            raise NoReplicaAvailable(
                "disagg: prefill stage starved", reasons=reasons,
                retry_after_s=retry_after)
        # co-located short-circuit: when the prefill and decode stages
        # resolve to the SAME single replica (one mixed-role replica —
        # common in shakedown topologies), a two-stage attempt can only
        # self-handoff and land in the fallback path. Serve it directly
        # instead: not a failure, so it counts colocated, not fallbacks.
        dprobe = self.router.stage_candidates("decode")
        if dprobe and \
                {r.replica_id for r in cands} == \
                {r.replica_id for r in dprobe} and len(
                    {r.replica_id for r in cands}) == 1:
            _c_colocated.inc()
            return self.router.submit(
                prompt_ids, max_new_tokens, deadline=deadline,
                priority=priority, on_token=on_token)
        prefill_rep = None
        phandle = None
        for rep in cands:
            try:
                _faults.site("disagg.prefill")
                phandle = rep.engine.submit(
                    prompt_ids, max_new_tokens, deadline=deadline,
                    priority=priority, prefill_only=True)
                prefill_rep = rep
                break
            except (NotReadyError, QueueFullError, RuntimeError) as e:
                reasons[rep.replica_id] = type(e).__name__
                ra = getattr(e, "retry_after_s", None)
                if ra is not None:
                    retry_after = ra if retry_after is None \
                        else min(retry_after, ra)
        if prefill_rep is None:
            reasons["no-prefill-replica"] = \
                f"all {len(cands)} prefill candidate(s) refused"
            raise NoReplicaAvailable(
                "disagg: prefill stage starved", reasons=reasons,
                retry_after_s=retry_after)
        if not prefill_rep.engine._background:
            prefill_rep.engine.run_until_idle()
        toks = phandle.result(timeout=self.prefill_timeout_s)
        preq = phandle._req
        if not toks:
            # the prefill stage terminated without a first token
            # (cancelled / timed out / shed): nothing to hand off and
            # nothing to fall back to — surface the terminal handle
            return phandle
        first_token = toks[0]
        root = preq.span
        ctx = root.context() if root.recording else None

        # -- stage 2: transfer + decode admission ----------------------
        err = None
        try:
            t0 = time.perf_counter_ns()
            frame, exported = kv_transfer.export_prefix(
                prefill_rep.engine.cache, prompt_ids)
            dec_reasons = {}
            dcands = self.router.stage_candidates(
                "decode", exclude={prefill_rep.replica_id},
                reasons=dec_reasons, allow_remote=self._remote_ok)
            if not dcands:
                reasons.update(dec_reasons)
                reasons["no-decode-replica"] = \
                    "no READY decode-stage candidate"
                raise TransferError("disagg: decode stage starved")
            pol = resilience.policy("disagg.transfer", max_attempts=2,
                                    retry_on=(TransferError,
                                              ConnectionError,
                                              TimeoutError))
            for rep in dcands:
                try:
                    if rep.engine is None:
                        # engine-less candidate: the decode stage lives
                        # in ANOTHER process — admission + token relay
                        # ride the rpc transport (module docstring).
                        # Refuse an advertised pool-geometry mismatch
                        # BEFORE the frame ships (GeometryMismatch is a
                        # TransferError: the sweep records the reason
                        # and moves to the next candidate)
                        kv_transfer.check_geometry(
                            kv_transfer.geometry(
                                prefill_rep.engine.cache),
                            (rep.member or {}).get("kv_geom"),
                            who=f"disagg.decode.{rep.replica_id}")
                        handle = self._remote_handoff(
                            rep, prefill_rep, preq, ctx, prompt_ids,
                            first_token, max_new_tokens, deadline,
                            priority, on_token, frame, exported, t0)
                    else:
                        state = {"maybe_sent": False}

                        def _send(rep=rep, state=state):
                            _faults.site("disagg.transfer")
                            if state["maybe_sent"]:
                                # re-shipping after an AMBIGUOUS
                                # timeout: the remote may already hold
                                # the frame — import dedups, but the
                                # duplicate send is never silent
                                _c_dup_frames.inc()
                            try:
                                return self.transport.send(rep, frame)
                            except TransferTimeout:
                                state["maybe_sent"] = True
                                raise
                        imported = resilience.retry_call(_send,
                                                         policy=pol)
                        try:
                            handle = rep.engine.submit_handoff(
                                prompt_ids, first_token,
                                max_new_tokens, deadline=deadline,
                                priority=priority, on_token=on_token,
                                trace_parent=ctx,
                                transfer_us=(time.perf_counter_ns()
                                             - t0) / 1000.0,
                                transfer_bytes=exported.nbytes)
                        except BaseException:
                            # admission refused AFTER the import
                            # landed: eagerly sweep the freshly
                            # imported refcount-0 blocks back to the
                            # free list — a failed handoff must not
                            # park blocks until LRU pressure
                            kv_transfer.release_import(
                                rep.engine.cache, imported)
                            raise
                except (TransferError, HandoffError, NotReadyError,
                        QueueFullError, ConnectionError, TimeoutError,
                        RuntimeError) as e:
                    reasons[rep.replica_id] = type(e).__name__
                    err = e
                    continue
                dur_us = (time.perf_counter_ns() - t0) / 1000.0
                _c_handoffs.inc()
                if rep.engine is None:
                    _c_remote.inc()
                _c_transfer_bytes.inc(exported.nbytes)
                _c_transfer_us.inc(dur_us)
                _tracing.record_span(
                    "serving.transfer", root, dur_us,
                    nbytes=exported.nbytes, blocks=exported.blocks,
                    src=prefill_rep.replica_id, dst=rep.replica_id)
                return handle
            reasons["transfer-failed"] = \
                f"all {len(dcands)} decode candidate(s) refused " \
                f"({type(err).__name__ if err else 'unknown'})"
            raise err if err is not None else TransferError(
                "disagg: transfer failed")
        except (TransferError, HandoffError, NotReadyError,
                QueueFullError, ConnectionError, TimeoutError,
                RuntimeError) as e:
            # -- fail open: co-located serving on the prefill replica.
            # Its prefix cache still holds the prompt's blocks, so the
            # fallback re-plans to full coverage — no re-prefill, no
            # lost request, a broken fabric degrades instead of failing
            _c_fallbacks.inc()
            resilience.degrade(
                "disagg.fallback",
                detail=f"prefill={prefill_rep.replica_id} "
                       f"rid={preq.rid}", exc=e)
            try:
                return prefill_rep.engine.submit(
                    prompt_ids, max_new_tokens, deadline=deadline,
                    priority=priority, on_token=on_token)
            except (NotReadyError, QueueFullError, RuntimeError) as fe:
                reasons[prefill_rep.replica_id] = type(fe).__name__
                reasons.setdefault("transfer-failed",
                                   type(e).__name__)
                ra = getattr(fe, "retry_after_s", None)
                if ra is not None:
                    retry_after = ra if retry_after is None \
                        else min(retry_after, ra)
                raise NoReplicaAvailable(
                    "disagg: transfer failed and co-located fallback "
                    "refused", reasons=reasons,
                    retry_after_s=retry_after) from fe

    # -- remote (cross-process) decode stage ----------------------------

    def _remote_handoff(self, rep, prefill_rep, preq, ctx, prompt_ids,
                        first_token, max_new_tokens, deadline,
                        priority, on_token, frame, exported, t0):
        """Admit the decode stage on a remote host and return the
        relay-backed handle. The request_id derives from the prefill
        identity + frame digest, so every retry of THIS submit reuses
        one identity and the remote admission dedups; the admit rpc
        itself retries only the AMBIGUOUS/refused-dial channel
        failures (``disagg.admit`` policy) — a structured remote
        refusal (HandoffError, geometry mismatch…) propagates to the
        candidate sweep / fail-open ladder unchanged."""
        digest = hashlib.blake2b(bytes(frame),
                                 digest_size=16).hexdigest()
        request_id = f"{prefill_rep.replica_id}.{preq.rid}." \
                     f"{digest[:8]}"
        req_kw = {
            "request_id": request_id, "frame_digest": digest,
            "frame": bytes(frame), "prompt_ids": prompt_ids,
            "first_token": int(first_token),
            "max_new_tokens": int(max_new_tokens),
            "priority": priority,
            "deadline_s": (deadline.remaining()
                           if deadline is not None else None),
            "trace_parent": ctx,
            "transfer_us": (time.perf_counter_ns() - t0) / 1000.0,
            "transfer_bytes": exported.nbytes,
            "lease_ttl_s": self.lease_ttl_s,
        }
        state = {"maybe_sent": False}

        def _admit():
            _faults.site("disagg.admit")
            if state["maybe_sent"]:
                _c_dup_frames.inc()  # admission re-ships the frame
            try:
                return self.transport.admit(rep, req_kw)
            except TransferTimeout:
                state["maybe_sent"] = True
                raise
        resilience.retry_call(
            _admit, policy=resilience.policy(
                "disagg.admit", max_attempts=3,
                retry_on=(TransferTimeout, ConnectionError)))
        lease = resilience.Lease(f"disagg/{request_id}",
                                 self.lease_ttl_s)
        return RemoteHandoffHandle(
            self, rep, prefill_rep, preq, prompt_ids, max_new_tokens,
            deadline, priority, on_token, request_id, lease)


class RemoteHandoffHandle:
    """Caller-side view of a remote (cross-process) decode stage.

    Mirrors the routed-handle surface (``status``/``rid``/``tokens``/
    ``cost``/``result``/``stream``/``cancel``) over a PULL relay. The
    exactly-once mechanism is the MONOTONE CURSOR, not the transport:
    every ``_advance`` asks ``_rpc_pull`` for tokens past
    ``len(delivered)`` and appends only what comes back, so a retried
    or duplicated pull can never re-deliver a position to the caller's
    sinks. Liveness is the lease: successful pulls renew it, and when
    the relay flaps, a fresh fleet heartbeat on the decode replica's
    member payload renews it too (both rungs behind the
    ``disagg.lease`` fault site). Expiry before terminal — or a LOUD
    stale-cursor refusal (the decode host restarted or swept us) —
    reclaims ownership: fail open to co-located decode on the prefill
    replica, suppressing the first ``len(delivered)`` tokens of the
    replay (greedy-determinism contract, the ``RoutedHandle`` failover
    discipline applied cross-host)."""

    def __init__(self, pipeline, replica, prefill_rep, preq,
                 prompt_ids, max_new_tokens, deadline, priority,
                 on_token, request_id, lease):
        self._pipeline = pipeline
        self._replica = replica
        self._prefill_rep = prefill_rep
        self._preq = preq
        self._prompt = prompt_ids
        self._mnt = int(max_new_tokens)
        self._deadline = deadline
        self._priority = priority
        self._on_token = on_token
        self.request_id = str(request_id)
        self.lease = lease
        self._toks = []
        self._status = RequestStatus.RUNNING
        self._terminal = False
        self._error = None
        self._cost = None
        self._cancel_requested = False
        self._fb = None          # co-located handle after reclaim
        self.reclaimed = False
        self._lock = threading.RLock()

    # -- routed-handle surface -----------------------------------------

    @property
    def replica_id(self):
        return (self._prefill_rep.replica_id if self._fb is not None
                else self._replica.replica_id)

    @property
    def status(self):
        return self._status

    @property
    def rid(self):
        return self.request_id

    @property
    def trace_id(self):
        return getattr(self._preq, "trace_id", None)

    def tokens(self):
        with self._lock:
            return list(self._toks)

    def cost(self):
        with self._lock:
            return self._fb.cost() if self._fb is not None \
                else self._cost

    def cancel(self):
        with self._lock:
            self._cancel_requested = True
            if self._fb is not None:
                self._fb.cancel()
                return
        try:
            self._pipeline.transport.cancel(self._replica,
                                            self.request_id)
        except Exception:  # noqa: BLE001 — the relay surfaces
            pass           # CANCELLED, or the lease reclaims

    def result(self, timeout=None):
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        while True:
            with self._lock:
                if self._terminal:
                    if self._error is not None:
                        raise self._error
                    return list(self._toks)
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    raise TimeoutError(
                        f"remote handoff {self.request_id} not "
                        f"finished within {timeout}s")
                self._advance(left)

    def stream(self, timeout=None):
        """Yield tokens as the relay delivers them; ends at a terminal
        status (exactly-once across reclaim — see class docstring)."""
        i = 0
        while True:
            with self._lock:
                toks = list(self._toks)
                terminal, err = self._terminal, self._error
            while i < len(toks):
                yield toks[i]
                i += 1
            if terminal:
                if err is not None:
                    raise err
                return
            with self._lock:
                if not self._terminal:
                    self._advance(timeout)

    # -- relay internals (caller holds self._lock) ---------------------

    def _emit(self, tok):
        self._toks.append(tok)
        if self._on_token is not None:
            self._on_token(tok)

    def _finish(self, status):
        self._status = status
        self._terminal = True

    def _sleep_poll(self, left):
        d = self._pipeline.relay_poll_s
        if left is not None:
            d = min(d, max(float(left), 0.0))
        if d > 0:
            time.sleep(d)

    def _advance(self, left=None):
        """One relay round: pull from the cursor, process, renew the
        lease on evidence, reclaim on expiry or stale cursor."""
        if self._terminal:
            return
        pull_timeout = max(0.2, self.lease.remaining())
        if left is not None:
            pull_timeout = min(pull_timeout, max(0.05, float(left)))
        try:
            _faults.site("disagg.relay")
            resp = self._pipeline.transport.pull(
                self._replica, self.request_id, len(self._toks),
                timeout=pull_timeout)
        except RelayError as e:
            # loud stale-cursor refusal: the decode host restarted
            # mid-lease or already swept us — never resync, reclaim
            self._reclaim(e)
            return
        except Exception as e:  # noqa: BLE001 — channel failure: any
            # flavor (refused dial, ambiguous timeout, remote error)
            # is survivable while the lease lasts
            self._renew_from_heartbeat()
            if self.lease.expired():
                _c_lease_expired.inc()
                self._reclaim(e)
            else:
                self._sleep_poll(left)
            return
        for t in resp.get("tokens", ()):
            self._emit(int(t))
        try:
            _faults.site("disagg.lease")
            self.lease.renew()
        except Exception:  # noqa: BLE001 — renewal plane severed
            # (injected or real): keep serving while the TTL lasts;
            # the expiry check above reclaims when it runs out
            pass
        st = resp.get("status")
        if st in RequestStatus.TERMINAL:
            self._cost = resp.get("cost")
            self._finish(st)
        elif not resp.get("tokens"):
            self._sleep_poll(left)

    def _renew_from_heartbeat(self):
        """The decode replica's fleet heartbeat is INDIRECT liveness:
        a fresh member payload renews the lease even while the relay
        channel itself flaps (same ``disagg.lease`` site — a chaos
        scenario severs both renewal rungs at once)."""
        try:
            self._pipeline.router.refresh()
        except Exception:  # noqa: BLE001 — registry flap ≠ peer death
            pass
        m = self._replica.member
        if not m or "heartbeat_ts" not in m:
            return
        age = time.time() - float(m["heartbeat_ts"])
        if age < min(self.lease.ttl_s,
                     float(m.get("ttl_s", self.lease.ttl_s))):
            try:
                _faults.site("disagg.lease")
                self.lease.renew()
            except Exception:  # noqa: BLE001 — severed renewal rung
                pass

    def _reclaim(self, exc):
        """Lease-driven ownership reclaim: the decode side is presumed
        dead (or has forgotten us). Fail open to co-located decode on
        the prefill replica — its prefix cache still covers the prompt
        — replaying from the cursor: the first ``len(delivered)``
        tokens of the replay are suppressed, so the caller's sinks see
        each position exactly once. Counted ``serving.disagg.
        reclaims`` (NOT ``fallbacks``: the handoff happened)."""
        self.reclaimed = True
        _c_reclaims.inc()
        resilience.degrade(
            "disagg.reclaim",
            detail=f"remote={self._replica.replica_id} "
                   f"rid={self.request_id} cursor={len(self._toks)}",
            exc=exc)
        try:  # a live-but-forgotten decode host must stop emitting
            self._pipeline.transport.cancel(self._replica,
                                            self.request_id)
        except Exception:  # noqa: BLE001 — it is presumed dead anyway
            pass
        if self._cancel_requested:
            self._finish(RequestStatus.CANCELLED)
            return
        if len(self._toks) >= self._mnt:
            # every token already streamed; only the terminal ack died
            self._finish(RequestStatus.DONE)
            return
        eng = self._prefill_rep.engine
        try:
            fb = eng.submit(self._prompt, self._mnt,
                            deadline=self._deadline,
                            priority=self._priority)
            if not eng._background:
                eng.run_until_idle()
            toks = fb.result(
                timeout=self._pipeline.prefill_timeout_s)
        except Exception as fe:  # noqa: BLE001 — reclaim exhausted:
            # the caller sees the fallback's error, terminally
            self._error = fe
            self._finish(RequestStatus.ERROR)
            return
        skip = len(self._toks)
        for t in toks[skip:]:
            self._emit(int(t))
        self._fb = fb
        self._finish(fb.status)
