"""Fleet cache plane: digest publication, cache-aware routing, peer
KV pulls.

PR 17/19 gave each replica a content-addressed prefix cache and a
crc-framed way to move registered KV blocks between pools — but the
fleet still behaves like N independent caches behind a cache-BLIND
router: a shared-system-prompt workload recomputes the same prefix on
every replica it lands on. This module makes the N caches act like
one, in three planes:

1. **Digest publication** — each replica folds a compact summary of
   its hot registered chunk digests into its fleet-registry heartbeat
   payload (:class:`DigestPublisher` via ``Registrar.add_extra``):
   newest-registration-first out of the pool's live index, capped at
   ``FLAGS_fleet_cache_digests`` entries, hex-encoded, with a ``seq``
   that only moves when the summary changes (delta-friendly: an
   unchanged advertisement is recognizable without set comparison).
   Store-less in-process fleets (every test/gate topology) get the
   same cadence from the router-side plane's rate-limited snapshot
   (:meth:`FleetCachePlane.publish`).
2. **Cache-aware routing** — the :class:`~.router.Router` computes the
   submitted prompt's ``chunk_digests`` ONCE per sweep and scales each
   candidate's existing ``health/(1+inflight)`` rank by
   ``1 + FLAGS_fleet_cache_weight * predicted_coverage`` where
   predicted coverage is the LEADING run of prompt digests present in
   the candidate's advertisement (digests chain, so a leading run is
   exactly a usable prefix). A misprediction can never produce a wrong
   result — digests only gate *placement* — and any scoring failure
   fails open to the pure health rank
   (``resilience.degrade('fleet_cache.score')``).
3. **Peer fill** — when the chosen replica's own pool covers LESS of
   the prompt than the best advertising peer, the router pulls the
   advertised blocks over the existing ``kv_transfer`` frame plane
   (``export_prefix`` on the peer — in-process directly, cross-process
   via ``disagg.RpcTransport`` + :func:`_rpc_export` — then the
   all-or-nothing deduping ``import_prefix`` into the chosen pool)
   BEFORE submitting, so ordinary admission sees the prefix resident
   and extends instead of re-prefilling. A stale advertisement (the
   peer evicted between heartbeat and pull) surfaces as
   ``export_prefix``'s non-resident :class:`~.kv_transfer.
   TransferError`; that — and every other pull failure — degrades to
   plain local prefill (``serving.fleet_cache.pull_fallbacks``,
   ``resilience.degrade('fleet_cache.pull')``), outputs bit-identical
   either way. Pull geometry is refused BEFORE a frame ships
   (:func:`~.kv_transfer.check_geometry` against the advertised
   ``kv_geom``). Pull time/bytes bill on the request like a disagg
   transfer (``Accountant.note_transfer``) and record a
   ``serving.fleet_pull`` span on its trace.

Counters: ``serving.fleet_cache.{published,coverage_hits,peer_pulls,
pull_bytes,pull_fallbacks}``. Fault sites: ``fleet_cache.publish``,
``fleet_cache.pull`` (docs/ROBUSTNESS.md). ``FLAGS_fleet_cache=0``
(default; read at Router AND ServingEngine construction, the
``FLAGS_serving_prefix_cache`` convention) builds neither publisher
nor plane: placement, payloads, and counters stay byte-for-byte
pre-fleet-cache (tools/fleet_cache_gate.py pins the silence).

The elasticity half of the fleet plane — the predictive autoscaler
that spawns/drains replicas off merged fleet pressure — lives in
``serving/autoscaler.py``.
"""

from __future__ import annotations

import time

import numpy as np

from ..core import flags as flags_mod
from ..core import resilience
from ..inference.paged import chunk_digests
from ..profiler import metrics as _metrics
from ..profiler import tracing as _tracing
from ..testing import faults as _faults
from . import kv_transfer
from .kv_transfer import GeometryMismatch, TransferError, check_geometry

__all__ = ["DigestPublisher", "FleetCachePlane", "geometry_payload",
           "GeometryMismatch", "check_geometry"]

_c_published = _metrics.counter("serving.fleet_cache.published")
_c_coverage_hits = _metrics.counter("serving.fleet_cache.coverage_hits")
_c_peer_pulls = _metrics.counter("serving.fleet_cache.peer_pulls")
_c_pull_bytes = _metrics.counter("serving.fleet_cache.pull_bytes")
_c_pull_fallbacks = _metrics.counter(
    "serving.fleet_cache.pull_fallbacks")


def geometry_payload(engine):
    """The pool-geometry half of a replica's registry payload
    (``kv_geom``): block size, kv dtype, head layout. Published
    UNCONDITIONALLY (pure mechanism, no flag) so remote admission
    (serving/disagg.py) and peer pulls can refuse a geometry mismatch
    BEFORE a frame ships — the PR 19 leftover."""
    return {"kv_geom": kv_transfer.geometry(engine.scheduler.cache)}


class DigestPublisher:
    """One replica's advertisement builder: the hot slice of its
    registered full-chunk digest set, hottest first — blocks a live
    request still references (most recently registered first), then
    the parked reclaimable LRU newest-first (the next-evicted digest
    is the LAST a peer should bet a pull on). Partial-tail keys are
    never advertised: a pull lands whole blocks or nothing.

    ``payload()`` is what rides the registry heartbeat
    (``Registrar.add_extra``) and what the router-side plane snapshots
    for store-less fleets; it walks live pool maps WITHOUT the engine
    lock (heartbeats must not wait out a device step), so a racing
    mutation can raise — callers treat any failure as "advertisement
    unchanged this beat" (``fleet_cache.publish`` discipline)."""

    __slots__ = ("engine", "cap", "_seq", "_last")

    def __init__(self, engine, cap=None):
        self.engine = engine
        self.cap = int(flags_mod.flag("FLAGS_fleet_cache_digests")
                       if cap is None else cap)
        self._seq = 0
        self._last = None

    def digests(self):
        """Hot-first capped list of registered full-chunk digests
        (raw bytes)."""
        cache = self.engine.scheduler.cache
        parked = list(cache._cached_free)
        parked_set = set(parked)
        # active = registered blocks NOT parked (a live request holds
        # them); _block_keys is insertion-ordered, newest registration
        # last — reverse for recency
        keyed = list(cache._block_keys.items())
        out, seen = [], set()

        def _add(block_ids):
            for b in block_ids:
                for kind, key in cache._block_keys.get(b, ()):
                    if kind != "full" or key in seen:
                        continue
                    seen.add(key)
                    out.append(key)
                    if len(out) >= self.cap:
                        return True
            return False

        if not _add(b for b, _ in reversed(keyed)
                    if b not in parked_set):
            _add(reversed(parked))
        return out

    def payload(self):
        """The heartbeat/advertisement dict:
        ``{"kv_digests": [hex...], "kv_digest_seq": n}`` — ``seq``
        moves only when the digest list changed, so consumers can skip
        unchanged advertisements without comparing sets. Counted
        ``serving.fleet_cache.published`` per build; fault site
        ``fleet_cache.publish``."""
        _faults.site("fleet_cache.publish")
        digs = tuple(d.hex() for d in self.digests())
        if digs != self._last:
            self._last = digs
            self._seq += 1
        _c_published.inc()
        return {"kv_digests": list(digs), "kv_digest_seq": self._seq}


class _Advert:
    """One replica's last-known advertisement, however it arrived
    (registry member payload or in-process snapshot)."""

    __slots__ = ("digests", "geom", "seq")

    def __init__(self, digests, geom=None, seq=0):
        self.digests = frozenset(digests)
        self.geom = geom
        self.seq = seq


class _RouteView:
    """One submit sweep's digest work, computed once: the prompt, its
    chunk-digest hexes, and every advertiser's predicted LEADING
    coverage in blocks."""

    __slots__ = ("ids", "hexes", "coverage", "block_size")

    def __init__(self, ids, hexes, coverage, block_size):
        self.ids = ids
        self.hexes = hexes
        self.coverage = coverage  # {replica_id: leading blocks}
        self.block_size = block_size


class _PullInfo:
    """What one successful peer fill did (the billing/span record)."""

    __slots__ = ("src", "us", "nbytes", "result")

    def __init__(self, src, us, nbytes, result):
        self.src = src
        self.us = us
        self.nbytes = nbytes
        self.result = result


class FleetCachePlane:
    """The router-side half: advertisement intake, coverage scoring,
    and the peer-fill ladder. Constructed by :class:`~.router.Router`
    when ``FLAGS_fleet_cache`` is set at construction; a disarmed
    router has NO plane and routes byte-for-byte health-rank.

    Advertisements come from two places, registry payload winning:
    a replica discovered via the fleet store carries ``kv_digests`` in
    its member payload (heartbeat cadence); in-process engine-bound
    replicas are snapshotted by :meth:`publish`, rate-limited to
    ``FLAGS_fleet_cache_publish_s`` on the submit path — tests and
    gates call ``publish(force=True)`` as their deterministic
    heartbeat tick. Either way an advertisement is a point-in-time
    claim that can go stale; the pull ladder treats staleness as an
    ordinary fallback, never an error the caller sees."""

    def __init__(self, router, publish_s=None):
        self.router = router
        self.weight = float(flags_mod.flag("FLAGS_fleet_cache_weight"))
        self.publish_s = float(
            flags_mod.flag("FLAGS_fleet_cache_publish_s")
            if publish_s is None else publish_s)
        self._ads = {}
        self._last_publish = None
        self._transport = None  # lazy disagg.RpcTransport (remote pulls)

    # -- advertisement intake -------------------------------------------

    def publish(self, force=False):
        """Snapshot every engine-bound replica's advertisement (the
        in-process heartbeat tick). Rate-limited unless ``force``; a
        replica whose publisher fails keeps its previous advertisement
        (heartbeat semantics: the old payload stands until
        overwritten)."""
        now = time.monotonic()
        if not force and self._last_publish is not None \
                and now - self._last_publish < self.publish_s:
            return
        self._last_publish = now
        for rep in self._known():
            pub = getattr(rep.engine, "_fleet_pub", None) \
                if rep.engine is not None else None
            if pub is None:
                continue
            try:
                p = pub.payload()
                self._ads[rep.replica_id] = _Advert(
                    p["kv_digests"],
                    geom=kv_transfer.geometry(rep.engine.scheduler.cache),
                    seq=p["kv_digest_seq"])
            except Exception as e:  # noqa: BLE001 — a failed snapshot
                # must not stop routing; the stale ad stands (the pull
                # ladder absorbs staleness)
                resilience.degrade(
                    "fleet_cache.publish",
                    detail=f"replica={rep.replica_id}", exc=e)

    def _known(self):
        with self.router._lock:
            return [self.router._replicas[rid]
                    for rid in self.router._order]

    def _ad_for(self, rep):
        m = rep.member
        if m is not None and m.get("kv_digests") is not None:
            return _Advert(m["kv_digests"], geom=m.get("kv_geom"),
                           seq=m.get("kv_digest_seq", 0))
        return self._ads.get(rep.replica_id)

    # -- coverage scoring -----------------------------------------------

    def rank(self, cands, prompt_ids):
        """Re-rank one sweep's candidates by coverage-scaled health;
        returns ``(cands, view)`` where ``view`` carries the per-
        advertiser coverage the peer-fill step reuses. Any failure
        fails open to the incoming health order (``view=None``) — a
        scoring bug must never cost a placement."""
        try:
            self.publish()
            ids = np.ascontiguousarray(
                np.asarray(prompt_ids).reshape(-1), dtype=np.int64)
            bs = self._block_size()
            if not bs or ids.size < bs:
                return cands, None
            hexes = [d.hex() for d in chunk_digests(ids, bs)]
            if not hexes:
                return cands, None
            cov = {}
            for rep in self._known():
                ad = self._ad_for(rep)
                if ad is None or not ad.digests:
                    continue
                if ad.geom is not None \
                        and ad.geom.get("block_size") != bs:
                    continue  # incomparable digests: different chunking
                n = 0
                for hx in hexes:
                    if hx not in ad.digests:
                        break
                    n += 1
                if n:
                    cov[rep.replica_id] = n
            view = _RouteView(ids, hexes, cov, bs)
            if cov:
                total = float(len(hexes))
                w = self.weight
                cands = sorted(
                    cands,
                    key=lambda r: -(
                        (r.health() / (1.0 + r.inflight()))
                        * (1.0 + w * cov.get(r.replica_id, 0) / total)))
            return cands, view
        except Exception as e:  # noqa: BLE001 — placement must survive
            # any scoring failure; health rank is always a right answer
            resilience.degrade("fleet_cache.score", exc=e)
            return cands, None

    def _block_size(self):
        for rep in self._known():
            if rep.engine is not None:
                return rep.engine.scheduler.cache.block_size
        return None

    # -- peer fill ------------------------------------------------------

    def peer_fill(self, rep, view):
        """Pull the best advertising peer's covered prefix into
        ``rep``'s pool before submit, when it beats what ``rep``
        already holds. Returns a :class:`_PullInfo` on success, None
        when no pull applies, and None — counted
        ``serving.fleet_cache.pull_fallbacks``, degraded
        ``fleet_cache.pull`` — on ANY failure: the request then
        prefills locally, bit-identical (coverage only changes where
        compute happens, never what it produces)."""
        peers = [(n, rid) for rid, n in view.coverage.items()
                 if rid != rep.replica_id]
        if not peers:
            return None
        best_n, best_rid = max(peers)
        try:
            local = rep.engine.scheduler.cache.plan_prefix(view.ids)
            if best_n <= local.matched_full:
                return None  # resident already beats the best ad
            _faults.site("fleet_cache.pull")
            t0 = time.perf_counter_ns()
            src = self.router._replicas.get(best_rid)
            if src is None:
                raise TransferError(
                    f"fleet_cache: advertiser {best_rid!r} left the "
                    f"fleet")
            pull_ids = view.ids[:best_n * view.block_size]
            frame = self._fetch(src, rep, pull_ids)
            result = kv_transfer.import_prefix(
                rep.engine.scheduler.cache, frame)
            us = (time.perf_counter_ns() - t0) / 1000.0
            _c_peer_pulls.inc()
            _c_pull_bytes.inc(result.nbytes)
            return _PullInfo(best_rid, us, result.nbytes, result)
        except Exception as e:  # noqa: BLE001 — the whole ladder fails
            # open: stale advertisement (export refuses non-resident),
            # geometry refusal, dead peer, exhausted destination pool —
            # all end in an ordinary local prefill
            _c_pull_fallbacks.inc()
            resilience.degrade(
                "fleet_cache.pull",
                detail=f"src={best_rid} dst={rep.replica_id} "
                       f"blocks={best_n}", exc=e)
            return None

    def _fetch(self, src, dst, pull_ids):
        """One peer's frame, geometry refused BEFORE it ships. In-
        process peers export directly (readiness irrelevant — a
        DRAINING peer's pool is still a fine read); engine-less
        advertisers answer over the disagg rpc fabric
        (:func:`_rpc_export`), retried once on a refused dial (nothing
        was sent; a re-fetch is free and the import dedups)."""
        local_geom = kv_transfer.geometry(dst.engine.scheduler.cache)
        if src.engine is not None:
            check_geometry(
                local_geom,
                kv_transfer.geometry(src.engine.scheduler.cache),
                who=f"fleet_cache.pull.{src.replica_id}")
            frame, _ = kv_transfer.export_prefix(
                src.engine.scheduler.cache, pull_ids)
            return frame
        check_geometry(local_geom, (src.member or {}).get("kv_geom"),
                       who=f"fleet_cache.pull.{src.replica_id}")
        if self._transport is None:
            from .disagg import RpcTransport
            self._transport = RpcTransport()
        return resilience.retry_call(
            self._transport._call, src.replica_id, _rpc_export,
            args=(src.replica_id, np.asarray(pull_ids).tolist()),
            policy=resilience.policy(
                "fleet_cache.pull", max_attempts=2,
                retry_on=(ConnectionError, TimeoutError)))

    # -- post-placement accounting --------------------------------------

    def note_routed(self, rep, handle, view, pull):
        """After a successful routed submit: count a coverage-informed
        placement, bill a pull's time/bytes on the request (the
        ``note_transfer`` discipline — informational, outside the
        step-closure sum), and put the pull on the request's trace."""
        try:
            if pull is not None or view.coverage.get(rep.replica_id):
                _c_coverage_hits.inc()
            req = getattr(handle, "_req", None)
            if pull is None or req is None:
                return
            rep.engine.scheduler.accounting.note_transfer(
                req, pull.us, pull.nbytes)
            _tracing.record_span(
                "serving.fleet_pull", req.span, pull.us,
                src=pull.src, dst=rep.replica_id, nbytes=pull.nbytes,
                blocks=pull.result.blocks_imported,
                deduped=pull.result.blocks_deduped)
        except Exception as e:  # noqa: BLE001 — bookkeeping must never
            # fail a request that already routed
            resilience.degrade("fleet_cache.score", exc=e)


def _rpc_export(name, token_ids):
    """Remote half of a cross-process peer pull — runs on the
    advertising host via ``distributed.rpc`` (the ``_rpc_import``
    mirror): export the registered prefix covering ``token_ids`` from
    the engine registered as ``name`` (``disagg.register_rpc_engine``
    — the same table every rpc-visible engine already sits in). Loud
    on an unregistered name or a non-resident prefix; the caller's
    pull ladder fails open."""
    from .disagg import _RPC_ENGINES
    eng = _RPC_ENGINES.get(str(name))
    if eng is None:
        raise TransferError(
            f"rpc export: no engine registered as {name!r} "
            f"(call disagg.register_rpc_engine on the peer host)")
    frame, _ = kv_transfer.export_prefix(eng.scheduler.cache,
                                         token_ids)
    return frame
