"""Thread-safe serving frontend: submit/stream/cancel over the
iteration scheduler.

``ServingEngine`` is the process-wide entry point a server loop (RPC
handler, HTTP worker pool, ...) calls from many threads:

- ``submit() -> RequestHandle`` — validated admission; the handle
  streams tokens incrementally (``stream()`` iterator, ``on_token``
  callback), waits for completion (``result()``), and cancels.
- a background **driver thread** (default) runs scheduler steps while
  work exists and sleeps on a condition otherwise; ``background=False``
  hands the stepping to the caller (``step()`` / ``run_until_idle()``)
  for deterministic tests and gates.
- per-request deadlines ride on ``core.resilience.Deadline``; expired
  requests finish with status ``TIMEOUT`` at the next step boundary.
- an explicit **lifecycle** (``WARMING -> READY -> DRAINING ->
  CLOSED``) served from ``/readyz`` — distinct from ``/healthz``
  liveness. ``submit()`` is accepted ONLY in READY: a WARMING engine
  rejects with ``NotReadyError`` exactly like a DRAINING one, so a
  request can never be billed a cold compile that ``warmup()`` should
  have paid — ``/readyz`` and submit semantics agree. ``warmup()``
  precompiles the bounded serving program set (every prefill bucket +
  the decode step; with the AOT cache armed this loads-or-stores
  serialized executables, so the NEXT process boots zero-compile)
  and flips WARMING -> READY. A graceful ``drain()``: admission
  stops (``NotReadyError``), every in-flight request finishes with
  its terminal status unchanged and outputs bit-identical to an
  undrained run, readiness flips, and the replica deregisters from
  the fleet registry (profiler/fleet.py). This is the drain contract
  the multi-replica router (serving/router.py) rolls deploys
  against (docs/SERVING.md).

One re-entrant lock guards all scheduler state, and the driver holds it
for the duration of a scheduling iteration (prefill + decode are device
calls) — so ``submit()``/``cancel()``/``tokens()`` are cheap host-side
operations that may nevertheless wait up to one in-flight step (or a
cold compile, on the very first requests) before acquiring the lock.
Don't call them on a thread that cannot tolerate ~one decode step of
latency. If the driver thread dies, every live request terminates with
``ERROR`` and the cause re-raises from ``submit``/``result`` — a
crashed engine never leaves a consumer blocked on a silent stream.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time

import numpy as np

from ..core import flags as flags_mod
from ..core import resilience
from ..profiler import metrics as _metrics
from ..profiler import tracing as _tracing
from .bucketing import bucket_lengths
from .scheduler import (AdmissionRejected, HandoffError,
                        QueueFullError, RequestStatus, Scheduler)

__all__ = ["ServingEngine", "RequestHandle", "QueueFullError",
           "AdmissionRejected", "RequestStatus", "Lifecycle",
           "NotReadyError", "HandoffError"]

# replica roles (disaggregated serving, serving/disagg.py): the fleet
# registry carries the role so a stage-aware router can rank prefill
# and decode candidates separately; "mixed" (the default) serves both
# stages co-located — existing fleets are untouched
ROLES = ("mixed", "prefill", "decode")

_SENTINEL = object()


class Lifecycle:
    """Replica readiness states (/readyz; docs/SERVING.md "Drain
    contract" / "Cold start & routing"): WARMING precompiles and
    rejects submits (``warmup()`` -> READY); READY is routable;
    DRAINING finishes in-flight work while rejecting new submits;
    CLOSED is terminal."""

    WARMING = "WARMING"
    READY = "READY"
    DRAINING = "DRAINING"
    CLOSED = "CLOSED"


class NotReadyError(RuntimeError):
    """Submission rejected because the engine is not READY (WARMING,
    DRAINING, or CLOSED) — the caller should route to another replica
    (or finish ``warmup()`` first)."""


_c_drain_started = _metrics.counter("serving.drain.started")
_c_drain_completed = _metrics.counter("serving.drain.completed")
_c_warmup_programs = _metrics.counter("serving.warmup.programs")
_h_warmup_us = _metrics.histogram(
    "serving.warmup_us",
    bounds=(10000, 100000, 500000, 1000000, 5000000, 30000000))
_g_lifecycle_ready = _metrics.gauge("serving.lifecycle.ready")


class RequestHandle:
    """Caller-side view of one request. Safe to use from any thread."""

    def __init__(self, engine):
        self._engine = engine
        self._req = None  # bound by ServingEngine.submit
        self._q = queue_mod.Queue()
        self._done = threading.Event()

    @property
    def rid(self):
        return self._req.rid

    @property
    def status(self):
        return self._req.status

    @property
    def preempts(self):
        return self._req.preempts

    @property
    def priority(self):
        """This request's priority class (serving/overload.py: smaller
        = more important; overload.NORMAL when the caller passed
        none)."""
        return self._req.priority

    @property
    def retry_after_s(self):
        """Back-off hint in seconds, set when this request was
        load-SHED (status ``SHED``) by the overload controller — the
        predicted time until the queue drains enough for a retry to
        stand a chance. None otherwise (including when the service-time
        model was not yet primed)."""
        return self._req.retry_after_s

    @property
    def trace_id(self):
        """This request's trace id (None when tracing is disabled or
        the trace was not sampled) — resolve it against the span ring
        (`profiler.tracing.export_trace`) or the `/traces/<id>`
        endpoint once the request is terminal."""
        return self._req.trace_id

    def tokens(self):
        """Tokens generated so far (stable snapshot)."""
        with self._engine._lock:
            return list(self._req.generated)

    def cost(self):
        """This request's :class:`~paddle_tpu.profiler.accounting.
        CostReport` — queue/prefill/decode/compile split of the device
        time attributed to it, token and prefix-coverage counts, and
        (once terminal) deadline_met. A detached snapshot, safe to keep;
        None when accounting is disarmed
        (``FLAGS_serving_accounting=0``)."""
        with self._engine._lock:
            c = self._req.cost
            return c.clone() if c is not None else None

    def cancel(self):
        self._engine.cancel(self)

    def stream(self, timeout=None):
        """Yield tokens as they are produced; ends when the request
        reaches a terminal status (check ``.status`` for CANCELLED /
        TIMEOUT / SHED — a shed request streamed nothing and carries
        ``retry_after_s``). If the ENGINE died the stream raises its fatal error
        instead of ending — truncated output must never look complete.
        ``timeout`` bounds the wait per token (queue.Empty past it)."""
        while True:
            item = self._q.get(timeout=timeout)
            if item is _SENTINEL:
                if self._req.status == RequestStatus.ERROR:
                    err = self._engine._error
                    if err is not None:
                        raise err
                return
            yield item

    def result(self, timeout=None):
        """Block until terminal; returns the generated tokens. Raises
        TimeoutError if the wait exceeds ``timeout``, or the engine's
        fatal error if serving itself died."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.rid} not finished within {timeout}s")
        if self._req.status == RequestStatus.ERROR:
            err = self._engine._error
            if err is not None:
                raise err
        return self.tokens()


class ServingEngine:
    """See module docstring. Construct once per model; context-manager
    friendly (``with ServingEngine(model) as eng: ...``)."""

    def __init__(self, model, *, max_batch=8, block_size=16,
                 max_seq_len=2048, num_blocks=None, temperature=0.0,
                 eos_token_id=None, dtype=None,
                 prefill_token_budget=None, max_queue=None,
                 bucket_cap=None, prefix_cache=None, accounting=None,
                 admission=None, brownout=None, kv_cache_dtype=None,
                 spec=None, spec_tokens=None, mesh=None,
                 background=True, ready=True, role=None,
                 paged_kernel=None):
        self._state = Lifecycle.WARMING
        # disaggregation role (serving/disagg.py): advertised through
        # the fleet registry and the stage-aware router; "mixed" is
        # byte-for-byte the pre-disagg engine
        self.role = "mixed" if role is None else str(role)
        if self.role not in ROLES:
            raise ValueError(
                f"ServingEngine: unknown role {role!r} "
                f"(expected one of {ROLES})")
        self._sched = Scheduler(
            model, max_batch=max_batch, block_size=block_size,
            max_seq_len=max_seq_len, num_blocks=num_blocks,
            temperature=temperature, eos_token_id=eos_token_id,
            dtype=dtype, prefill_token_budget=prefill_token_budget,
            max_queue=max_queue, bucket_cap=bucket_cap,
            prefix_cache=prefix_cache, accounting=accounting,
            admission=admission, brownout=brownout,
            kv_cache_dtype=kv_cache_dtype, spec=spec,
            spec_tokens=spec_tokens, mesh=mesh,
            paged_kernel=paged_kernel)
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._background = background
        self._thread = None
        self._closed = False
        self._error = None
        self._metrics_server = None
        self._registrar = None
        # fleet cache digest publication (serving/fleet_cache.py;
        # FLAGS_fleet_cache read here, the FLAGS_serving_prefix_cache
        # convention): disarmed = no publisher object, registry
        # payloads byte-for-byte pre-fleet-cache
        self._fleet_pub = None
        if bool(flags_mod.flag("FLAGS_fleet_cache")):
            from . import fleet_cache as _fleet_cache
            self._fleet_pub = _fleet_cache.DigestPublisher(self)
        # ready=False holds the engine in WARMING: submit() raises
        # NotReadyError until warmup() (or mark_ready()) flips READY;
        # routers see WARMING as not-routable on /readyz
        if ready:
            self._state = Lifecycle.READY
        _g_lifecycle_ready.set(1 if ready else 0)

    # -- submission ----------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens=32, *, deadline_s=None,
               deadline=None, priority=None, on_token=None,
               prefill_only=False):
        """Enqueue a request; returns a RequestHandle immediately.

        ``deadline_s`` (relative seconds) or ``deadline`` (a
        ``resilience.Deadline``) bounds total latency: expiry finishes
        the request with status TIMEOUT at the next step boundary and
        frees its blocks — and with the overload plane armed
        (``FLAGS_serving_admission``) a deadline the EWMA service-time
        model proves unmeetable raises ``AdmissionRejected`` HERE,
        with a ``retry_after_s``, instead of queueing doomed work.
        ``priority`` is an int class (serving/overload.py: smaller =
        more important, default ``overload.NORMAL``) — the shed order
        under pressure and the brownout ladder's admission floor.
        ``on_token(token)`` is called per generated token from the
        stepping thread — keep it fast.
        ``prefill_only`` (disaggregated serving, serving/disagg.py)
        runs ONLY the prefill stage: the request finishes ``DONE`` at
        its first token with the prompt's KV blocks registered for
        ``kv_transfer.export_prefix`` — requires the prefix cache.
        """
        handle = RequestHandle(self)

        def _sink_token(req, tok):
            handle._q.put(tok)
            if on_token is not None:
                on_token(tok)

        def _sink_finish(req):
            handle._q.put(_SENTINEL)
            handle._done.set()

        with self._cond:
            if self._closed:
                raise RuntimeError("ServingEngine is closed")
            if self._error is not None:
                raise RuntimeError(
                    "ServingEngine died; no new submissions") \
                    from self._error
            if self._state != Lifecycle.READY:
                # WARMING rejects like DRAINING: a request must never
                # silently pay the cold compiles warmup() owes
                # (/readyz and submit agree — test_router.py pins it)
                hint = "call warmup() first" \
                    if self._state == Lifecycle.WARMING \
                    else "route to another replica"
                raise NotReadyError(
                    f"ServingEngine is {self._state}; not accepting "
                    f"new requests ({hint})")
            if deadline is None and deadline_s is not None:
                deadline = resilience.Deadline.after(deadline_s)
            handle._req = self._sched.submit(
                prompt_ids, max_new_tokens, deadline=deadline,
                priority=priority, on_token=_sink_token,
                on_finish=_sink_finish, prefill_only=prefill_only)
            self._ensure_driver()
            self._cond.notify_all()
        return handle

    def submit_handoff(self, prompt_ids, first_token,
                       max_new_tokens=32, *, deadline_s=None,
                       deadline=None, priority=None, on_token=None,
                       trace_parent=None, transfer_us=0.0,
                       transfer_bytes=0, handoff_id=None):
        """Disaggregated decode-stage admission (serving/disagg.py):
        the prompt's KV blocks were imported into this engine's pool
        (``kv_transfer.import_prefix``) and ``first_token`` came from
        the prefill replica — possibly in ANOTHER process entirely
        (the rpc-served ``disagg._rpc_admit`` endpoint lands here) —
        admit straight into the batched decode step, zero prefill
        compute here. Same lifecycle gate as :meth:`submit`; the
        handle streams the FULL sequence (the first token re-emits
        through it). ``handoff_id`` (remote handoffs) is the
        pipeline-assigned cross-process identity, recorded on the
        admission span so the lease/relay records join the trace.
        Raises :class:`~.scheduler.HandoffError` when the imported
        prefix does not cover the prompt or no slot/blocks are free —
        the pipeline falls back to co-located serving."""
        handle = RequestHandle(self)

        def _sink_token(req, tok):
            handle._q.put(tok)
            if on_token is not None:
                on_token(tok)

        def _sink_finish(req):
            handle._q.put(_SENTINEL)
            handle._done.set()

        with self._cond:
            if self._closed:
                raise RuntimeError("ServingEngine is closed")
            if self._error is not None:
                raise RuntimeError(
                    "ServingEngine died; no new submissions") \
                    from self._error
            if self._state != Lifecycle.READY:
                hint = "call warmup() first" \
                    if self._state == Lifecycle.WARMING \
                    else "route to another replica"
                raise NotReadyError(
                    f"ServingEngine is {self._state}; not accepting "
                    f"new requests ({hint})")
            if deadline is None and deadline_s is not None:
                deadline = resilience.Deadline.after(deadline_s)
            handle._req = self._sched.admit_handoff(
                prompt_ids, first_token, max_new_tokens,
                deadline=deadline, priority=priority,
                on_token=_sink_token, on_finish=_sink_finish,
                trace_parent=trace_parent, transfer_us=transfer_us,
                transfer_bytes=transfer_bytes, handoff_id=handoff_id)
            self._ensure_driver()
            self._cond.notify_all()
        return handle

    def _ensure_driver(self):
        # caller holds the lock
        if self._background and self._thread is None:
            self._thread = threading.Thread(
                target=self._drive, name="paddle-tpu-serving",
                daemon=True)
            self._thread.start()

    def cancel(self, handle):
        with self._cond:
            self._sched.cancel(handle._req)
            self._cond.notify_all()

    # -- stepping ------------------------------------------------------

    @property
    def has_work(self):
        return self._sched.has_work

    @property
    def scheduler(self):
        return self._sched

    @property
    def cache(self):
        return self._sched.cache

    @property
    def accounting(self):
        """The engine's cost accountant (profiler/accounting.py): the
        null accountant when disarmed. ``engine.accounting.
        engine_report()`` / ``.goodput_line()`` aggregate goodput."""
        return self._sched.accounting

    @property
    def alerts(self):
        """The engine's AlertManager (None when accounting is
        disarmed); also served from the MetricsServer's /alerts."""
        return self._sched.alerts

    def step(self):
        """Run one scheduling iteration (foreground mode, or extra
        nudges in background mode)."""
        with self._lock:
            return self._sched.step()

    def run_until_idle(self):
        """Step until the scheduler is idle (foreground mode). Results
        arrive via the handles. Purely a stepping helper — admission
        stays open and the lifecycle does not move (contrast
        :meth:`drain`, the graceful shutdown)."""
        while True:
            with self._lock:
                if not self._sched.has_work:
                    return
            self.step()

    # -- lifecycle -----------------------------------------------------

    @property
    def lifecycle(self):
        """Current :class:`Lifecycle` state (served from /readyz)."""
        return self._state

    def warmup(self):
        """Precompile the bounded serving program set — every prefill
        bucket the config can produce (``bucket_lengths``: the
        log2(cap) ladder) plus the batched decode step — then flip
        WARMING -> READY. This is the cold-start gate: constructed
        with ``ready=False``, an engine rejects submits until warmup
        finishes, so live traffic NEVER pays a first-bucket compile.
        With the AOT cache armed (serving/aot_cache.py) each program
        loads from the on-disk store when warm (zero XLA compiles —
        tools/router_gate.py pins a warm second process) or compiles
        once and is stored for the next process.

        Runs the real jit entry points against throwaway slots (freed
        afterward; no requests exist in WARMING, so the pool is
        untouched by traffic). Idempotent — re-running in READY just
        revisits warm programs; raises past DRAINING like
        ``mark_ready``. Returns the number of programs visited."""
        with self._lock:
            if self._state in (Lifecycle.DRAINING, Lifecycle.CLOSED):
                raise RuntimeError(
                    f"cannot warmup a {self._state} engine")
            sched = self._sched
            cache = sched.cache
            buckets = bucket_lengths(cache.block_size, sched.bucket_cap,
                                     sched.max_seq_len)
            t0 = time.perf_counter_ns()
            n = 0
            # role-specialized warm sets (disaggregated serving):
            # prefill replicas run ONLY the bucket ladder (they never
            # decode), decode replicas warm ONLY the decode/spec
            # programs (handoffs never prefill here) — mixed warms both
            decoded = self.role == "prefill"
            if self.role == "decode":
                buckets = []
                slot = cache.alloc_slot(cache.block_size)
                if slot is not None:
                    try:
                        active = np.zeros((cache.max_batch,), bool)
                        active[slot] = True
                        sched.model.paged_decode_step(
                            cache, np.zeros((cache.max_batch,),
                                            np.int64), active,
                            temperature=sched.temperature,
                            kernel_mode=getattr(sched, "kernel_mode",
                                                None))
                        n += 1
                        if sched.spec:
                            sk = sched.spec_tokens
                            sched.model.paged_spec_step(
                                cache,
                                np.zeros((cache.max_batch,), np.int64),
                                np.zeros((cache.max_batch, sk),
                                         np.int64),
                                np.full((cache.max_batch,), 1 + sk,
                                        np.int64), active)
                            n += 1
                    finally:
                        cache.free_slot(slot)
            with _tracing.span("serving.warmup", buckets=len(buckets)):
                for b in buckets:
                    slot = cache.alloc_slot(b)
                    if slot is None:
                        continue  # pool smaller than the ladder tail
                    try:
                        ids = np.zeros((b,), np.int64)
                        sched.model.paged_prefill(
                            cache, slot, ids,
                            temperature=sched.temperature, pad_to=b)
                        n += 1
                        if not decoded:
                            # one decode step warms the (single) decode
                            # program; the next-position write past the
                            # allocated blocks lands in the null block,
                            # the bucketing convention
                            active = np.zeros((cache.max_batch,), bool)
                            active[slot] = True
                            sched.model.paged_decode_step(
                                cache, np.zeros((cache.max_batch,),
                                                np.int64), active,
                                temperature=sched.temperature,
                                kernel_mode=getattr(sched,
                                                    "kernel_mode",
                                                    None))
                            decoded = True
                            n += 1
                            if sched.spec:
                                # the speculative verify sweep is one
                                # more static program — warm it too so
                                # the first live spec step never
                                # compiles (junk writes land past the
                                # slot or in the null block; the slot
                                # is freed below)
                                sk = sched.spec_tokens
                                sched.model.paged_spec_step(
                                    cache,
                                    np.zeros((cache.max_batch,),
                                             np.int64),
                                    np.zeros((cache.max_batch, sk),
                                             np.int64),
                                    np.full((cache.max_batch,), 1 + sk,
                                            np.int64), active)
                                n += 1
                    finally:
                        cache.free_slot(slot)
            _c_warmup_programs.inc(n)
            _h_warmup_us.observe((time.perf_counter_ns() - t0) / 1000.0)
        try:
            from ..distributed import watchdog
            watchdog.record_event("serving.warmup",
                                  meta={"programs": n}, status="lifecycle")
        except Exception:  # noqa: BLE001 — telemetry must not block boot
            pass
        if self._state == Lifecycle.WARMING:
            self.mark_ready()
        return n

    def mark_ready(self):
        """WARMING -> READY (no-op in READY; raises past that — a
        drained replica never becomes routable again)."""
        with self._cond:
            if self._state in (Lifecycle.DRAINING, Lifecycle.CLOSED):
                raise RuntimeError(
                    f"cannot mark_ready a {self._state} engine")
            self._state = Lifecycle.READY
            _g_lifecycle_ready.set(1)

    def drain(self, timeout=60):
        """Graceful shutdown of ADMISSION, not of the process: flips
        READY -> DRAINING (new ``submit()`` raises
        :class:`NotReadyError`; routers see /readyz go 503), lets
        every in-flight request finish naturally — terminal statuses
        unchanged, outputs bit-identical to an undrained run
        (tools/fleet_gate.py pins zero dropped requests) — then flips
        DRAINING -> CLOSED and deregisters from the fleet registry so
        routers drop the replica immediately. The metrics endpoint
        stays up for a final scrape; ``close()`` tears it down.
        Idempotent; ``timeout`` bounds the in-flight wait in
        background mode (TimeoutError past it, state stays DRAINING
        so a retry can finish the job). If the ENGINE dies mid-drain
        the drain is NOT graceful — the in-flight requests terminated
        ERROR, so the engine error re-raises here (state still flips
        CLOSED and the replica deregisters: a dead replica must leave
        the registry either way, but it never reports a clean
        ``serving.drain.completed``)."""
        with self._cond:
            if self._state == Lifecycle.CLOSED:
                return
            first = self._state != Lifecycle.DRAINING
            self._state = Lifecycle.DRAINING
            _g_lifecycle_ready.set(0)
            inflight = self._sched.inflight()
            span = _tracing.start_trace("serving.drain",
                                        inflight=inflight) \
                if first else _tracing.NULL
            if first:
                _c_drain_started.inc()
            self._cond.notify_all()
        if first:
            self._record_drain("started", inflight)
        # complete in-flight work: the background driver keeps
        # stepping (DRAINING is not CLOSED); foreground steps inline
        if self._thread is not None and self._thread.is_alive():
            deadline = None if timeout is None \
                else time.monotonic() + float(timeout)
            with self._cond:
                while self._sched.has_work and self._error is None:
                    if deadline is not None and \
                            time.monotonic() >= deadline:
                        span.end("timeout")
                        raise TimeoutError(
                            f"drain: {self._sched.inflight()} requests "
                            f"still in flight after {timeout}s")
                    self._cond.wait(0.02)
        else:
            with self._lock:
                while self._sched.has_work and self._error is None:
                    self._sched.step()
        with self._cond:
            was_closed = self._state == Lifecycle.CLOSED
            self._state = Lifecycle.CLOSED
            reg, self._registrar = self._registrar, None
            err = self._error
        if reg is not None:
            reg.deregister()
        if err is not None:
            # the driver died mid-drain: requests terminated ERROR,
            # not gracefully — never report a clean completion
            span.annotate(completed=False)
            span.end("error")
            raise RuntimeError(
                "drain: engine died before in-flight work could "
                "finish") from err
        if not was_closed:  # a concurrent drain lost the race: one edge
            _c_drain_completed.inc()
            self._record_drain("completed", 0)
        # the span belongs to the FIRST drainer, which may not be the
        # thread that won the CLOSED transition — end it regardless
        span.annotate(completed=True)
        span.end("CLOSED")

    @staticmethod
    def _record_drain(phase, inflight):
        """Flight-record the drain edges so post-mortems show deploys
        interleaved with the traffic around them."""
        try:
            from ..distributed import watchdog
            watchdog.record_event(f"serving.drain.{phase}",
                                  meta={"inflight": inflight},
                                  status="lifecycle")
        except Exception:  # noqa: BLE001 — telemetry must not block a drain
            pass

    def _drive(self):
        try:
            while True:
                with self._cond:
                    while not self._sched.has_work:
                        if self._closed:
                            return
                        self._cond.wait()
                    if self._closed and not self._sched.has_work:
                        return
                self.step()
        except BaseException as e:  # noqa: BLE001 — fail loud, not silent
            with self._cond:
                self._error = e
                self._sched.fail_all(e)
            resilience.degrade("serving.engine", exc=e)

    # -- telemetry export ----------------------------------------------

    def serve_metrics(self, port=0, host="127.0.0.1", store=None,
                      replica_id=None):
        """Attach a scrapeable telemetry endpoint to this engine
        (idempotent; closed with the engine). Routes: ``/metrics``
        (OpenMetrics text), ``/metrics/delta`` (per-second rates),
        ``/healthz`` (SLO gauges + engine liveness — 503 once the
        driver died or the engine closed), ``/readyz`` (the drain
        lifecycle — 503 unless READY), ``/alerts`` (SLO burn-rate
        incidents from this engine's AlertManager), ``/traces`` and
        ``/traces/<id>`` (Chrome/Perfetto span exports). ``port=0``
        (the default) binds an ephemeral port — ALWAYS read the bound
        one from ``.port``/``.url()`` on the returned server instead of
        hardcoding (multi-replica routers discover replicas this way).

        ``store`` (a ``distributed.store.TCPStore`` client) opts this
        replica into the FLEET REGISTRY (profiler/fleet.py): the scrape
        address + identity self-register under a TTL'd heartbeat, so a
        FleetAggregator discovers, scrapes, and health-scores it;
        ``drain()``/``close()`` deregister. With ``FLAGS_fleet=0`` or
        no store this is a byte-for-byte no-op (no thread, fleet.*
        counters silent)."""
        with self._lock:
            if self._metrics_server is None:
                from ..profiler.export import MetricsServer
                self._metrics_server = MetricsServer(
                    port=port, host=host, health_extra=self._health_view,
                    alerts=self._sched.alerts, ready=self._ready_view)
            srv = self._metrics_server
            register = store is not None and self._registrar is None \
                and self._state not in (Lifecycle.DRAINING,
                                        Lifecycle.CLOSED)
        if register:
            from ..profiler import fleet as _fleet
            if _fleet.armed(store):
                reg = _fleet.Registrar(
                    store, srv.url(""), replica_id=replica_id,
                    status_fn=lambda: self._state, role=self.role)
                # pool geometry rides every payload UNCONDITIONALLY
                # (serving/fleet_cache.geometry_payload): peers refuse
                # a frame-exchange mismatch BEFORE anything ships
                from . import fleet_cache as _fleet_cache
                reg.add_extra(
                    lambda: _fleet_cache.geometry_payload(self))
                if self._fleet_pub is not None:
                    # the digest advertisement (FLAGS_fleet_cache,
                    # read at construction) joins the same beat
                    reg.add_extra(self._fleet_pub.payload)
                reg.start()
                with self._lock:
                    if self._registrar is None:
                        self._registrar = reg
                    else:  # lost an unlikely double-attach race
                        reg.deregister()
        return srv

    def _health_view(self):
        with self._lock:
            alive = self._error is None and not self._closed
            view = {"engine": {
                "closed": self._closed,
                "lifecycle": self._state,
                "queue": len(self._sched.queue),
                "running": len(self._sched.running)}}
            if self._error is not None:
                view["engine"]["error"] = \
                    f"{type(self._error).__name__}: {self._error}"
        if not alive:
            view["status"] = "draining" if self._error is None \
                else "dead"
        return view

    def _ready_view(self):
        """/readyz body: routability, distinct from /healthz liveness —
        a DRAINING replica is alive (scrape it!) but must receive no
        new traffic."""
        with self._lock:
            state = self._state
            body = {"ready": state == Lifecycle.READY
                    and self._error is None,
                    "state": state, "attached": True,
                    "inflight": self._sched.inflight()}
            if self._error is not None:
                body["error"] = \
                    f"{type(self._error).__name__}: {self._error}"
        return body

    # -- lifecycle -----------------------------------------------------

    def close(self, cancel_pending=True, timeout=60):
        """Stop serving. ``cancel_pending=True`` (default) cancels all
        live requests (they finish CANCELLED at the final sweep);
        ``False`` drains them first."""
        with self._cond:
            self._closed = True
            self._state = Lifecycle.CLOSED
            _g_lifecycle_ready.set(0)
            reg, self._registrar = self._registrar, None
            if cancel_pending:
                for req in list(self._sched.queue):
                    req.cancel_requested = True
                for req in list(self._sched.running.values()):
                    req.cancel_requested = True
            self._cond.notify_all()
        if reg is not None:
            reg.deregister()  # routers drop us before the join below
        if self._thread is not None:
            self._thread.join(timeout)
        # foreground mode (or a dead driver): flush remaining work so
        # every handle reaches a terminal status
        with self._lock:
            if self._error is None:
                while self._sched.has_work:
                    self._sched.step()
            server, self._metrics_server = self._metrics_server, None
        if server is not None:
            server.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
