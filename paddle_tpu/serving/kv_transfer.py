"""KV-block export/import plane for disaggregated prefill/decode.

The fabric half of ``serving/disagg.py``: after a prefill-role replica
finishes a prompt's bucket-ladder pass, its finished KV rows already
sit in content-addressed paged blocks (``inference/paged.py`` —
registered under rolling ``chunk_digests`` by ``commit_prefix``). This
module serializes exactly those rows into a self-describing, crc-
guarded frame and lands them into ANOTHER replica's pool, registering
the same digests there — so the decode replica's ordinary admission
path (``plan_prefix`` -> full coverage -> ``alloc_slot_cached``)
admits the handed-off request with ZERO re-prefill compute.

Contract:

- **Block-aligned, digest-keyed.** A frame carries the prompt's full
  chunks (and its partially-filled tail block, under the same
  ``_partial_key`` the prefix cache uses) with their K/V rows per
  layer. Quantized pools ship int8 data AND the float32 scale rows
  together — the pair is the value; splitting them would silently
  dequantize garbage.
- **Bit-exact.** Rows cross the wire as raw host arrays of the pool's
  storage dtype; import writes them back with ``.at[block].set``. A
  round trip changes no bits, which is what keeps greedy decode on the
  importing replica bit-identical to co-located serving
  (tools/disagg_gate.py pins it, fp32 and int8).
- **Checkpoint-v2 framing.** ``MAGIC + crc32 + length + payload``
  (the serving/aot_cache.py discipline): a short, truncated, or
  bit-flipped frame fails loudly at the boundary — import raises
  :class:`TransferError` BEFORE touching the pool, never lands a
  partial prefix.
- **Validated before mutation.** Geometry (layers/heads/head_dim/
  block_size/kv dtype) must match the destination cache, and the
  digests are recomputed from the frame's own token ids — a frame
  whose digests do not re-derive is rejected loudly (tampered or
  mis-keyed payloads must not poison the prefix index).
- **First registration wins.** A digest already resident in the
  destination pool keeps its local block (the ``commit_prefix`` rule);
  imported duplicates are dropped, so shared-prefix traffic across
  many handoffs converges to one block per chunk.

Imported blocks land refcount-0 in the reclaimable LRU (exactly the
state a finished request's registered blocks park in), so they are
admissible by the next request and evictable under pressure — the
import is indistinguishable from "this replica prefilled the prompt
itself and the request finished" as far as the pool is concerned.

No flags and no counters here: this plane is pure mechanism. The
``FLAGS_serving_disagg`` gate, the ``serving.disagg.*`` counters, the
rpc streaming, and the fail-open ladder all live in
``serving/disagg.py`` — a disarmed pipeline never calls into here, so
flag-off stays byte-for-byte silent.
"""

from __future__ import annotations

import pickle
import struct
import zlib

import numpy as np

from ..inference.paged import _partial_key, chunk_digests

__all__ = ["TransferError", "TransferTimeout", "RelayError",
           "GeometryMismatch", "ExportedPrefix", "ImportResult",
           "export_prefix", "import_prefix", "release_import",
           "pack_frame", "unpack_frame", "geometry",
           "check_geometry", "MAGIC"]

MAGIC = b"PTPUKVT1"
_HEADER = struct.Struct(">4sQ")  # crc32 (raw big-endian) + payload len
_VERSION = 1


class TransferError(RuntimeError):
    """A KV frame was rejected: corrupt framing, geometry mismatch,
    digest mismatch, non-resident source prefix, or a destination pool
    without room. Always raised BEFORE any destination-pool mutation —
    the caller (serving/disagg.py) fails open to co-located serving."""


class TransferTimeout(TransferError):
    """The fabric timed out AFTER the frame left this host: delivery
    is UNKNOWN — the remote may have imported (or admitted) it and the
    ack was lost. Distinct from a refused dial (plain
    ``ConnectionRefusedError``: nothing was sent, retry is free).
    Retrying after THIS is safe only because both remote operations
    are idempotent — import dedups resident digests, admission dedups
    on (request_id, frame digest) — but it re-ships the frame, counted
    ``serving.disagg.dup_frames`` rather than silently merged."""


class GeometryMismatch(TransferError):
    """Two pools cannot exchange frames: block size, kv dtype, or head
    layout differ. Structured — ``who`` names the refusing site (e.g.
    ``disagg.decode.<rid>``, ``fleet_cache.pull.<rid>``, ``import``)
    and ``mismatch`` maps each differing field to ``(theirs, ours)`` —
    so the refusal is diagnosable from the exception alone. Raised
    BEFORE a frame ships whenever the counterpart pre-registered its
    geometry (``kv_geom`` in the fleet-registry payload —
    serving/fleet_cache.geometry_payload); :func:`import_prefix`'s
    frame-time validation raises it too, as the backstop for peers
    that never advertised."""

    def __init__(self, who, mismatch):
        self.who = str(who)
        self.mismatch = dict(mismatch)
        super().__init__(
            f"{self.who}: pool geometry mismatch — " + "; ".join(
                f"{k}: theirs={t!r} ours={o!r}"
                for k, (t, o) in sorted(self.mismatch.items())))


def check_geometry(local_geom, advertised, who="kv"):
    """Refuse a transfer BEFORE any frame ships: compare a
    counterpart's ADVERTISED registry geometry against this pool's.
    A missing/empty advertisement passes — a peer predating geometry
    pre-registration still gets frame-time validation — but an
    advertisement that disagrees on ANY field raises
    :class:`GeometryMismatch` naming every differing field."""
    if not advertised:
        return
    diff = {k: (advertised.get(k), local_geom[k]) for k in local_geom
            if advertised.get(k) != local_geom[k]}
    if diff:
        raise GeometryMismatch(who, diff)


class RelayError(RuntimeError):
    """The token relay refused a cursor: the decode host has no record
    of the request (it restarted mid-lease, or swept the lease as
    orphaned) or the cursor runs past its buffer. Deliberately LOUD and
    non-retryable — a stale cursor must trigger reclaim/fail-open, not
    a quiet resync that could double- or skip-emit tokens."""


class ExportedPrefix:
    """An export's host-side summary (the frame itself is ``bytes``)."""

    __slots__ = ("num_tokens", "full_chunks", "partial_len", "nbytes")

    def __init__(self, num_tokens, full_chunks, partial_len, nbytes):
        self.num_tokens = num_tokens
        self.full_chunks = full_chunks
        self.partial_len = partial_len
        self.nbytes = nbytes

    @property
    def blocks(self):
        return self.full_chunks + (1 if self.partial_len else 0)


class ImportResult:
    """What an import did to the destination pool. ``blocks`` lists
    the block ids the import freshly allocated (dedups excluded) — the
    exact set :func:`release_import` can sweep back if the handed-off
    request never admits or its lease dies."""

    __slots__ = ("num_tokens", "blocks_imported", "blocks_deduped",
                 "nbytes", "blocks")

    def __init__(self, num_tokens, blocks_imported, blocks_deduped,
                 nbytes, blocks=()):
        self.num_tokens = num_tokens
        self.blocks_imported = blocks_imported
        self.blocks_deduped = blocks_deduped
        self.nbytes = nbytes
        self.blocks = list(blocks)


# -- framing (the serving/aot_cache.py checkpoint-v2 discipline) -----------

def pack_frame(payload):
    """``MAGIC + crc32 + length + payload`` — the only bytes that ever
    cross the fabric."""
    return MAGIC + _HEADER.pack(
        zlib.crc32(payload).to_bytes(4, "big"), len(payload)) + payload


def unpack_frame(frame):
    """Validate framing and return the payload, or raise
    :class:`TransferError` naming the first check that failed (short
    frame -> magic -> length -> crc, the aot_cache load order)."""
    if not isinstance(frame, (bytes, bytearray, memoryview)):
        raise TransferError(
            f"kv frame: expected bytes, got {type(frame).__name__}")
    frame = bytes(frame)
    if len(frame) < len(MAGIC) + _HEADER.size:
        raise TransferError(
            f"kv frame: short frame ({len(frame)} bytes)")
    if frame[:len(MAGIC)] != MAGIC:
        raise TransferError("kv frame: bad magic")
    crc_b, length = _HEADER.unpack_from(frame, len(MAGIC))
    payload = frame[len(MAGIC) + _HEADER.size:]
    if len(payload) != length:
        raise TransferError(
            f"kv frame: length mismatch (header {length}, "
            f"payload {len(payload)})")
    if zlib.crc32(payload) != int.from_bytes(crc_b, "big"):
        raise TransferError("kv frame: crc mismatch")
    return payload


def geometry(cache):
    """A pool's exchange-relevant shape: what frames embed, what
    replicas pre-register in their fleet payload (``kv_geom``), and
    what :func:`check_geometry` compares. Plain JSON-serializable
    scalars — it rides heartbeat payloads verbatim."""
    return {"num_layers": cache.num_layers,
            "num_kv_heads": cache.num_kv_heads,
            "head_dim": cache.head_dim,
            "block_size": cache.block_size,
            "kv_dtype": cache.kv_dtype,
            "dtype": np.dtype(cache.dtype).name
            if not cache.quantized else "int8"}


_geometry = geometry  # pre-PR-20 internal name


# -- export ----------------------------------------------------------------

def export_prefix(cache, token_ids):
    """Serialize the finished KV blocks covering ``token_ids`` out of
    ``cache`` into a crc-framed transfer frame.

    The prefix must be FULLY resident (every full chunk registered,
    plus the partial tail when the prompt is not block-aligned) — on a
    prefill replica that just ran the prompt through ``commit_prefix``
    it always is; anything less raises :class:`TransferError` (the
    blocks were evicted under pressure, and a partial handoff would
    re-prefill on the decode side, which the gate forbids).

    Returns ``(frame_bytes, ExportedPrefix)``. Pure read — refcounts,
    indices, and pools are untouched.
    """
    ids = np.ascontiguousarray(np.asarray(token_ids).reshape(-1),
                               dtype=np.int64)
    plan = cache.plan_prefix(ids)
    if plan.covered_tokens != plan.num_tokens:
        raise TransferError(
            f"export: prefix not fully resident ({plan.covered_tokens}"
            f"/{plan.num_tokens} tokens covered)")
    blocks = list(plan.matched_blocks)
    partial = None
    if plan.partial_block is not None:
        parent = plan.digests[-1] if plan.digests else b""
        partial = {"len": plan.partial_len,
                   "key": _partial_key(
                       parent, ids[plan.num_tokens - plan.partial_len:])}
        blocks.append(plan.partial_block)
    idx = np.asarray(blocks, np.int32)
    k_rows = [np.asarray(cache.k_pools[i][idx])
              for i in range(cache.num_layers)]
    v_rows = [np.asarray(cache.v_pools[i][idx])
              for i in range(cache.num_layers)]
    obj = {"version": _VERSION, "geom": _geometry(cache), "ids": ids,
           "digests": list(plan.digests), "partial": partial,
           "k": k_rows, "v": v_rows, "k_scales": None, "v_scales": None}
    if cache.quantized:
        obj["k_scales"] = [np.asarray(cache.k_scales[i][idx])
                           for i in range(cache.num_layers)]
        obj["v_scales"] = [np.asarray(cache.v_scales[i][idx])
                           for i in range(cache.num_layers)]
    frame = pack_frame(pickle.dumps(obj, protocol=4))
    return frame, ExportedPrefix(plan.num_tokens, plan.matched_full,
                                 plan.partial_len, len(frame))


# -- import ----------------------------------------------------------------

def _validate(obj, cache):
    """Every rejection BEFORE any pool mutation."""
    if obj.get("version") != _VERSION:
        raise TransferError(
            f"import: unsupported frame version {obj.get('version')!r}")
    want, got = geometry(cache), obj.get("geom") or {}
    if got != want:
        diff = {k: (got.get(k), want[k]) for k in want
                if got.get(k) != want[k]}
        raise GeometryMismatch("import", diff)
    ids = np.ascontiguousarray(np.asarray(obj["ids"]).reshape(-1),
                               dtype=np.int64)
    digests = chunk_digests(ids, cache.block_size)
    if digests != list(obj["digests"]):
        raise TransferError(
            "import: digest mismatch (frame digests do not re-derive "
            "from its token ids)")
    partial = obj.get("partial")
    rem = ids.size - len(digests) * cache.block_size
    if partial is not None:
        parent = digests[-1] if digests else b""
        key = _partial_key(parent, ids[ids.size - int(partial["len"]):])
        if int(partial["len"]) != rem or key != partial["key"]:
            raise TransferError(
                "import: partial-tail key mismatch")
    elif rem:
        raise TransferError(
            f"import: frame covers {len(digests) * cache.block_size} of "
            f"{ids.size} tokens (missing partial tail)")
    n_rows = len(digests) + (1 if partial is not None else 0)
    for name in ("k", "v") + (("k_scales", "v_scales")
                              if cache.quantized else ()):
        rows = obj.get(name)
        if (not isinstance(rows, list) or len(rows) != cache.num_layers
                or any(r.shape[0] != n_rows for r in rows)):
            raise TransferError(f"import: malformed {name} rows")
    return ids, digests, partial, n_rows


def import_prefix(cache, frame):
    """Land a transfer frame's blocks into ``cache`` and register their
    digests, so the next ``plan_prefix`` over the same prompt reports
    full coverage and ``alloc_slot_cached`` maps the imported blocks
    read-only — zero re-prefill.

    All-or-nothing: framing, geometry, and digests are validated and
    every needed block is allocated BEFORE the first row lands; any
    failure raises :class:`TransferError` with the destination pool
    exactly as it was. Digests already resident are deduped (their
    local block wins). Returns :class:`ImportResult`.
    """
    payload = unpack_frame(frame)
    try:
        obj = pickle.loads(payload)
    except Exception as e:  # noqa: BLE001 — crc passed but the pickle
        # is still hostile/garbled: same loud rejection as bad framing
        raise TransferError(f"import: undecodable payload ({e!r})") \
            from e
    ids, digests, partial, n_rows = _validate(obj, cache)

    # plan the landing: (payload row index, digest-or-key) needing a
    # fresh block vs already-resident dedups
    land = []     # (row_index, kind, key)
    deduped = 0
    for i, d in enumerate(digests):
        if d in cache._prefix_index:
            deduped += 1
        else:
            land.append((i, "full", d))
    if partial is not None:
        if partial["key"] in cache._partial_index:
            deduped += 1
        else:
            land.append((len(digests), "part", partial["key"]))
    if len(land) > cache.num_free_blocks():
        raise TransferError(
            f"import: destination pool has {cache.num_free_blocks()} "
            f"allocatable blocks, frame needs {len(land)}")
    taken = []
    for _ in land:
        b = cache._take_block()
        if b is None:  # sliced pools can under-deliver vs the estimate
            for tb in reversed(taken):
                cache._deref_block(tb)
            raise TransferError(
                "import: destination pool exhausted mid-allocation")
        taken.append(b)
    if taken:
        src = np.asarray([i for i, _, _ in land], np.int64)
        dst = np.asarray(taken, np.int64)
        for i in range(cache.num_layers):
            cache.k_pools[i] = cache.k_pools[i].at[dst].set(
                np.asarray(obj["k"][i])[src])
            cache.v_pools[i] = cache.v_pools[i].at[dst].set(
                np.asarray(obj["v"][i])[src])
            if cache.quantized:
                cache.k_scales[i] = cache.k_scales[i].at[dst].set(
                    np.asarray(obj["k_scales"][i])[src])
                cache.v_scales[i] = cache.v_scales[i].at[dst].set(
                    np.asarray(obj["v_scales"][i])[src])
    # register, then park refcount-0 in the reclaimable LRU — byte-for-
    # byte the state commit_prefix + free_slot leaves local blocks in
    for (_, kind, key), b in zip(land, taken):
        idx = cache._prefix_index if kind == "full" \
            else cache._partial_index
        idx[key] = b
        cache._block_keys.setdefault(b, []).append((kind, key))
        cache._deref_block(b)
    return ImportResult(int(ids.size), len(taken), deduped,
                        len(bytes(frame)), blocks=taken)


def release_import(cache, result):
    """Sweep a fresh import's blocks back to the TRULY-free list.

    The undo for an import whose request never made it: admission
    refused after the frame landed (serving/disagg.py fails open
    elsewhere), or the remote handoff's lease died with the blocks
    parked (orphan reclamation). Without this the refcount-0 imports
    linger in the reclaimable LRU until capacity pressure evicts them —
    correct but occupying, and invisible to "did we leak" accounting.

    Only blocks still in the EXACT state the import left them (parked
    refcount-0 in ``_cached_free``) are touched; a block another
    request admitted against, or the LRU already evicted, is skipped —
    it is no longer this import's to reclaim. Eviction goes through
    ``_drop_cached`` so ``serving.prefix.evictions`` moves and the
    digest registrations drop with the block. Returns the number of
    blocks released. Safe to call twice (second call finds nothing).
    """
    released = 0
    for b in getattr(result, "blocks", ()):
        if b in cache._cached_free and int(cache._refcount[b]) == 0:
            cache._drop_cached(b)
            cache._free.append(b)
            released += 1
    return released
