"""Prefill length bucketing: bounded warm jit-cache footprint.

The prefill program is traced per padded prompt shape, so serving raw
lengths compiles an unbounded set of XLA executables (one per distinct
block-multiple length) — a production killer: every novel prompt length
pays a multi-second compile mid-serve. Bucketing rounds the padded
length up to the next power of two (capped by
``FLAGS_serving_prefill_bucket_cap``), so at most ``log2(cap)`` prefill
programs exist after warmup, whatever traffic arrives.

The extra padding is dead compute only: positions past the true length
are masked in attention, and pool writes past the slot's allocated
blocks land in the reserved null block 0 (see
``Llama.paged_prefill``). Lengths beyond the cap fall back to plain
block-multiple padding (they are rare by construction — cap at your p99
prompt length).

Pinned by the compile-count test in tests/framework/test_serving.py and
the no-recompile check in tools/serving_gate.py, both via the
``xla.compile.count`` metric (profiler.metrics' jax.monitoring
listener).

Interaction with prefix caching (``FLAGS_serving_prefix_cache``):
chunk hashes are computed over the UNPADDED token ids before any
bucketing — padding must never poison a content hash, or two prompts
that merely share a bucket would alias. The padded KV the prefill
writes past the true length is garbage but harmless: every reader
masks by seq_len, sharers of a partially-filled block copy-on-write
before their own tokens land, and decode appends overwrite those rows
in place. Cache-hitting admissions bucket only their uncovered TAIL
(the covered prefix is mapped, not computed), so the warm program set
stays bounded by the same log2(cap) ladder.
"""

from __future__ import annotations

__all__ = ["bucket_length", "bucket_lengths"]


def _round_up(n, multiple):
    return -(-n // multiple) * multiple


def bucket_length(n_tokens, block_size, cap, max_len=None):
    """Padded prefill length for a prompt of ``n_tokens``.

    Power-of-two bucket >= n_tokens (and >= block_size), rounded up to a
    block multiple, as long as the bucket fits under ``cap``; otherwise
    the plain block-multiple pad. ``max_len`` (the cache's
    max_blocks_per_seq * block_size) clamps the result either way.
    """
    if n_tokens < 1:
        raise ValueError(f"bucket_length: n_tokens must be >= 1, "
                         f"got {n_tokens}")
    base = _round_up(n_tokens, block_size)
    out = base
    if cap and cap > 0:
        p = max(block_size, 1)
        while p < n_tokens:
            p <<= 1
        p = _round_up(max(p, base), block_size)
        if p <= cap:
            out = p
    if max_len is not None:
        # clamp to the cache's capacity, but never below the minimal
        # block-multiple pad (callers validate n_tokens <= max_len)
        out = max(min(out, _round_up(max_len, block_size)), base)
    return out


def bucket_lengths(block_size, cap, max_len):
    """Every bucket a serving config can produce, ascending — what a
    warmup loop should prefill through so live traffic never compiles."""
    out, seen = [], set()
    n = 1
    while n <= max_len:
        b = bucket_length(n, block_size, cap, max_len)
        if b not in seen:
            seen.add(b)
            out.append(b)
        n = b + 1
    return out
