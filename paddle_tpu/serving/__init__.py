"""Production-shaped LLM serving over the paged KV cache.

The layer the ROADMAP's "serves heavy traffic" north star needs on top
of `inference.paged`: an iteration-level continuous-batching scheduler
(admission control + prefill budgeting + preemption instead of
truncation), a thread-safe streaming frontend with per-request
deadlines and cancellation, prefill length bucketing for a bounded
warm jit-cache footprint, and SLO telemetry in the always-on metrics
registry (``serving.*``, surfaced by ``profiler.summary()``).

    from paddle_tpu.serving import ServingEngine

    eng = ServingEngine(model, max_batch=8, max_seq_len=2048)
    h = eng.submit(prompt_ids, max_new_tokens=128, deadline_s=30.0)
    for tok in h.stream():
        ...
    assert h.status == "DONE"

See docs/SERVING.md for the scheduling policy, the preemption
contract, and the metric catalog.
"""

from .bucketing import bucket_length, bucket_lengths  # noqa: F401
from .frontend import (Lifecycle, NotReadyError,  # noqa: F401
                       QueueFullError, RequestHandle, RequestStatus,
                       ServingEngine)
from .scheduler import Scheduler, ServingRequest  # noqa: F401

__all__ = ["ServingEngine", "RequestHandle", "RequestStatus",
           "QueueFullError", "Lifecycle", "NotReadyError",
           "Scheduler", "ServingRequest",
           "bucket_length", "bucket_lengths"]
