"""Production-shaped LLM serving over the paged KV cache.

The layer the ROADMAP's "serves heavy traffic" north star needs on top
of `inference.paged`: an iteration-level continuous-batching scheduler
(admission control + prefill budgeting + preemption instead of
truncation), a thread-safe streaming frontend with per-request
deadlines and cancellation, prefill length bucketing for a bounded
warm jit-cache footprint, SLO telemetry in the always-on metrics
registry (``serving.*``, surfaced by ``profiler.summary()``), and the
zero-cold-start control plane: a persistent AOT compile cache
(``aot_cache`` — a fresh process with a warm cache boots without one
XLA compile), an explicit ``ServingEngine.warmup()`` gate
(WARMING -> READY), and an SLO-weighted multi-replica ``Router``
(``router`` — health-weighted placement, drain redistribution,
exactly-once failover), and the overload control plane (``overload``
— deadline-aware admission that fails fast with ``AdmissionRejected``,
priority load shedding to terminal status ``SHED``, a hysteresis-
guarded brownout ladder, and per-replica router circuit breakers).

    from paddle_tpu.serving import ServingEngine

    eng = ServingEngine(model, max_batch=8, max_seq_len=2048)
    h = eng.submit(prompt_ids, max_new_tokens=128, deadline_s=30.0)
    for tok in h.stream():
        ...
    assert h.status == "DONE"

See docs/SERVING.md for the scheduling policy, the preemption
contract, and the metric catalog.
"""

from . import aot_cache  # noqa: F401
from . import autoscaler  # noqa: F401
from . import disagg  # noqa: F401
from . import fleet_cache  # noqa: F401
from . import kv_transfer  # noqa: F401
from . import mesh  # noqa: F401
from . import overload  # noqa: F401
from . import spec  # noqa: F401
from .autoscaler import FleetAutoscaler  # noqa: F401
from .bucketing import bucket_length, bucket_lengths  # noqa: F401
from .disagg import DisaggPipeline  # noqa: F401
from .fleet_cache import FleetCachePlane  # noqa: F401
from .frontend import (AdmissionRejected, HandoffError,  # noqa: F401
                       Lifecycle, NotReadyError, QueueFullError,
                       RequestHandle, RequestStatus, ServingEngine)
from .kv_transfer import GeometryMismatch, TransferError  # noqa: F401
from .router import (NoReplicaAvailable, RoutedHandle,  # noqa: F401
                     Router, RouterReplica)
from .scheduler import Scheduler, ServingRequest  # noqa: F401

__all__ = ["ServingEngine", "RequestHandle", "RequestStatus",
           "QueueFullError", "AdmissionRejected", "Lifecycle",
           "NotReadyError", "HandoffError", "Scheduler",
           "ServingRequest", "Router", "RouterReplica", "RoutedHandle",
           "NoReplicaAvailable", "DisaggPipeline", "TransferError",
           "GeometryMismatch", "FleetCachePlane", "FleetAutoscaler",
           "aot_cache", "autoscaler", "disagg", "fleet_cache",
           "kv_transfer", "overload", "mesh",
           "bucket_length", "bucket_lengths"]
