"""Iteration-level continuous-batching scheduler (Orca-style) over the
paged KV cache.

Single-threaded policy core of the serving subsystem (thread safety is
the frontend's job — `serving.frontend.ServingEngine` holds one lock
around every entry point). Each ``step()`` is one scheduling iteration:

1. **sweep** — cancellations and expired deadlines (``core.resilience.
   Deadline``) finish at the step boundary and free their blocks;
2. **admit** — strict FCFS from a bounded queue, limited by free slots,
   free blocks, and a per-step *prefill token budget*
   (``FLAGS_serving_prefill_budget``) so a burst of long prompts cannot
   starve running decodes; admitted prompts prefill at a bucketed
   length (`serving.bucketing`) and stream their first token. With
   prefix caching on (``FLAGS_serving_prefix_cache``), a prompt's
   resident prefix blocks are mapped read-only instead of recomputed:
   the budget is charged for the *uncovered* tail only, and the
   prefill runs the tail-extend program (zero FLOPs for covered
   blocks);
3. **decode** — ONE jitted step for every live slot. Pool exhaustion
   preempts the newest-admitted victim (free blocks + requeue at the
   queue front for re-prefill) instead of truncating anyone —
   ``serving.preempt`` counts it, and greedy outputs stay bit-identical
   to an uncontended run because re-prefill replays prompt+generated
   and the prefill's sampled token is the next new token. With
   speculation armed (``FLAGS_serving_spec``, greedy only), the step
   instead runs ONE batched multi-position verify sweep over
   prompt-lookup drafts (``_decode_spec``; docs/SERVING.md "Decode
   speed tiers") — several tokens per request per step, still
   bit-identical, rejected rows rolled back.

Every request terminates in exactly one of ``DONE`` / ``CANCELLED`` /
``TIMEOUT`` / ``SHED`` (or ``ERROR`` if the engine itself died). SLO
telemetry goes to the always-on registry under ``serving.*`` (TTFT /
inter-token
latency histograms, queue/slot/KV-utilization gauges, admitted/decoded/
preempted counters) and is surfaced by ``profiler.summary()``.

With accounting armed (``FLAGS_serving_accounting``, default on), each
step's measured wall time is apportioned across the requests that did
work in it (``profiler/accounting.py``: tokens-proportional, compile
billed to the triggering request, re-prefill billed to the preemption)
into per-request ``CostReport``s and engine goodput, and the SLO
burn-rate alert rules (``profiler/alerts.py``) are evaluated at step
boundaries.

With the overload control plane armed (``FLAGS_serving_admission`` /
``FLAGS_serving_brownout``; ``serving/overload.py``), ``submit()``
additionally rejects provably-unmeetable deadlines immediately
(``AdmissionRejected`` with a ``retry_after_s``), each step sheds
lowest-priority/newest queued requests past the pressure watermarks
(terminal status ``SHED``, blocks never allocated), and a brownout
ladder degrades service gracefully under sustained overload.
"""

from __future__ import annotations

import math
import time

import numpy as np

from ..core import flags as flags_mod
from ..core import resilience
from ..inference.paged import (CapacityError, PagedKVCache,
                               kernel_route, quant_block_ratio,
                               resolve_kv_dtype, resolve_paged_kernel,
                               sized_num_blocks, validate_request)
from ..profiler import accounting as _accounting
from ..profiler import alerts as _alerts
from ..profiler import metrics as _metrics
from ..profiler import tracing as _tracing
from . import mesh as _mesh
from . import overload as _overload
from . import spec as _spec
from .bucketing import bucket_length
from .overload import AdmissionRejected

__all__ = ["RequestStatus", "ServingRequest", "Scheduler",
           "QueueFullError", "AdmissionRejected", "HandoffError"]


class HandoffError(RuntimeError):
    """A disaggregated handoff admission failed on the decode side:
    the imported prefix does not fully cover the prompt, or the
    replica is out of slots/blocks right now. Raised BEFORE the
    request exists — serving/disagg.py catches it and fails open to
    co-located serving (the request is never lost)."""


class QueueFullError(RuntimeError):
    """Admission queue at FLAGS_serving_max_queue: backpressure — the
    caller should retry later or shed load upstream. Carries structured
    fields (``queue_depth``, ``max_queue``, ``retry_after_s`` — the
    overload controller's predicted drain time, None when disarmed or
    unprimed) so routers and clients back off by data, not by parsing
    the message."""

    def __init__(self, message, *, queue_depth=None, max_queue=None,
                 retry_after_s=None):
        super().__init__(message)
        self.queue_depth = queue_depth
        self.max_queue = max_queue
        self.retry_after_s = retry_after_s


class RequestStatus:
    QUEUED = "QUEUED"
    RUNNING = "RUNNING"
    DONE = "DONE"
    CANCELLED = "CANCELLED"
    TIMEOUT = "TIMEOUT"
    SHED = "SHED"
    ERROR = "ERROR"

    TERMINAL = (DONE, CANCELLED, TIMEOUT, SHED, ERROR)


class ServingRequest:
    """One request's full lifecycle state. ``generated`` only ever
    appends (preemption keeps it), so handle readers see a stable
    prefix."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "deadline",
                 "on_token", "on_finish", "status", "generated", "slot",
                 "preempts", "admit_seq", "submitted_at", "admitted_at",
                 "first_token_at", "last_token_at", "cancel_requested",
                 "span", "cost", "priority", "est_tokens",
                 "retry_after_s", "prefill_only")

    def __init__(self, rid, prompt, max_new_tokens, deadline=None,
                 on_token=None, on_finish=None,
                 priority=_overload.NORMAL):
        self.rid = rid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.deadline = deadline
        self.on_token = on_token
        self.on_finish = on_finish
        self.status = RequestStatus.QUEUED
        self.generated = []
        self.slot = -1
        self.preempts = 0
        self.admit_seq = -1
        self.submitted_at = time.monotonic()
        self.admitted_at = None
        self.first_token_at = None
        self.last_token_at = None
        self.cancel_requested = False
        # root span of this request's trace: opened at submit, ended at
        # the terminal status; the null span when unsampled/disabled
        self.span = _tracing.NULL
        # CostReport bound by the accountant at submit; None disarmed
        self.cost = None
        # overload control plane (serving/overload.py): priority class
        # (smaller = more important), the controller's estimated
        # uncovered-prefill tokens, and — set only when this request is
        # load-SHED — the predicted back-off seconds for the caller
        self.priority = priority
        self.est_tokens = 0
        self.retry_after_s = None
        # disaggregated serving (serving/disagg.py): a prefill-stage
        # request finishes DONE at its first token — the decode stage
        # runs on another replica after the KV handoff
        self.prefill_only = False

    @property
    def trace_id(self):
        return self.span.trace_id

    @property
    def done(self):
        return self.status in RequestStatus.TERMINAL


# -- SLO instrumentation (always-on registry; see docs/SERVING.md) -------
_US_BOUNDS = (500, 1000, 2500, 5000, 10000, 25000, 50000, 100000,
              250000, 500000, 1000000, 5000000)
_m_admitted = _metrics.counter("serving.admitted")
_m_decoded = _metrics.counter("serving.decoded_tokens")
_m_preempt = _metrics.counter("serving.preempt")
_m_done = _metrics.counter("serving.completed")
_m_cancelled = _metrics.counter("serving.cancelled")
_m_timeout = _metrics.counter("serving.timeout")
_m_rejected = _metrics.counter("serving.rejected")
_m_shed = _metrics.counter("serving.shed")
_m_errors = _metrics.counter("serving.errors")
_m_cb_errors = _metrics.counter("serving.callback_errors")
_m_steps = _metrics.counter("serving.steps")
_h_ttft = _metrics.histogram("serving.ttft_us", bounds=_US_BOUNDS)
_h_itl = _metrics.histogram("serving.itl_us", bounds=_US_BOUNDS)
_h_queue_wait = _metrics.histogram("serving.queue_wait_us",
                                   bounds=_US_BOUNDS)
_h_step = _metrics.histogram("serving.step_us", bounds=_US_BOUNDS)
_g_queue = _metrics.gauge("serving.queue.depth")
_g_running = _metrics.gauge("serving.slots.running")
_g_blocks = _metrics.gauge("serving.kv.blocks_used")
_g_util = _metrics.gauge("serving.kv.utilization")
# prefix-cache economics: tokens the prefill actually computed (padded;
# covered tokens cost zero FLOPs — tools/prefix_gate.py pins this),
# blocks currently backing >1 slot, and reclaimable cached blocks
_m_prefix_computed = _metrics.counter("serving.prefix.computed_tokens")
_g_shared = _metrics.gauge("serving.kv.shared_blocks")
_g_cached = _metrics.gauge("serving.kv.cached_blocks")
# decode speed tiers (docs/SERVING.md "Decode speed tiers"): draft
# tokens proposed/accepted/rejected by the speculative verify sweep,
# its per-step acceptance rate, and the quantized-pool facts (bits +
# honest effective-capacity multiplier). All silent when both flags
# are off — tools/spec_gate.py pins the silence.
_m_spec_proposed = _metrics.counter("serving.spec.proposed")
_m_spec_accepted = _metrics.counter("serving.spec.accepted")
_m_spec_rejected = _metrics.counter("serving.spec.rejected")
_m_spec_steps = _metrics.counter("serving.spec.steps")
_h_spec_accept = _metrics.histogram(
    "serving.spec.accept_rate",
    bounds=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
_g_kv_quant_bits = _metrics.gauge("serving.kv.quant.bits")
_g_kv_quant_mult = _metrics.gauge(
    "serving.kv.quant.capacity_multiplier")
# per-THREAD cumulative backend-compile seconds (profiler.metrics'
# jax.monitoring listener): deltas around a prefill/decode dispatch
# attribute compile cost to the request that triggered it — a
# concurrent engine's compile on another thread never leaks into this
# scheduler's bills (profiler/accounting.py)
_compile_s = _metrics.thread_compile_seconds
# same delta discipline for compile seconds the AOT cache SAVED
# (serving/aot_cache.py): a dispatch that loaded a serialized
# executable bills the avoided compile as aot_saved_us — informational
# (never part of the closure sum), but per-request like compile itself
_aot_saved_s = None


def _saved_s():
    global _aot_saved_s
    if _aot_saved_s is None:
        from .aot_cache import thread_saved_seconds
        _aot_saved_s = thread_saved_seconds
    return _aot_saved_s()


class Scheduler:
    """See module docstring. NOT thread-safe — callers serialize."""

    def __init__(self, model, *, max_batch=8, block_size=16,
                 max_seq_len=2048, num_blocks=None, temperature=0.0,
                 eos_token_id=None, dtype=None,
                 prefill_token_budget=None, max_queue=None,
                 bucket_cap=None, prefix_cache=None, accounting=None,
                 admission=None, brownout=None, kv_cache_dtype=None,
                 spec=None, spec_tokens=None, mesh=None,
                 paged_kernel=None):
        import jax.numpy as jnp

        cfg = model.config
        self.model = model
        self.temperature = temperature
        self.eos_token_id = eos_token_id
        self.max_seq_len = max_seq_len
        mbps = math.ceil(max_seq_len / block_size)
        # mesh-sharded serving (FLAGS_serving_mesh, read ONCE at
        # construction like prefix_cache): the model axis tensor-
        # parallels params + KV pools via NamedSharding, the data axis
        # partitions slots/blocks into capacity slices; None (the
        # default '' / '1x1') is byte-for-byte single-device serving
        # with serving.mesh.* silence (serving/mesh.py)
        self.mesh = _mesh.resolve_serving_mesh(mesh)
        if self.mesh is not None:
            self.model.apply_serving_mesh(self.mesh)
            _mesh.note_engine(self.mesh)
        # int8 KV block storage (FLAGS_kv_cache_dtype, read ONCE at
        # construction like prefix_cache): default pool sizing grows by
        # the honest byte ratio — the same HBM budget holds ~2x the
        # blocks, compounding the prefix cache's capacity multiplier
        kv_dtype = resolve_kv_dtype(
            flags_mod.flag("FLAGS_kv_cache_dtype")
            if kv_cache_dtype is None else kv_cache_dtype)
        # paged-attention kernel routing (FLAGS_paged_kernel, read ONCE
        # at construction like kv_cache_dtype): the resolved mode rides
        # into every decode dispatch so the traced programs bake the
        # route; `kernel_route` names where it lands (pallas / interpret
        # / dense) for spans and gates
        self.kernel_mode = resolve_paged_kernel(paged_kernel)
        self.kernel_route = kernel_route(self.kernel_mode)
        hd = cfg.hidden_size // cfg.num_heads
        compute_dt = dtype if dtype is not None else jnp.bfloat16
        num_blocks = sized_num_blocks(
            num_blocks, max_batch, mbps, kv_dtype, hd, compute_dt)
        self.cache = PagedKVCache(
            cfg.num_layers, cfg.num_kv_heads, hd,
            num_blocks=num_blocks,
            block_size=block_size, max_blocks_per_seq=mbps,
            max_batch=max_batch, dtype=compute_dt, kv_dtype=kv_dtype,
            pool_sharding=(self.mesh.kv_pool_sharding()
                           if self.mesh is not None else None),
            scale_sharding=(self.mesh.kv_scale_sharding()
                            if self.mesh is not None else None),
            num_slices=(self.mesh.data if self.mesh is not None else 1))
        # per-slice KV gauges (slice-id label; docs/OBSERVABILITY.md):
        # registered only when the mesh is armed, so the disarmed
        # exposition is byte-for-byte pre-mesh
        self._slice_gauges = [
            {k: _metrics.gauge(f"serving.kv.{k}", labels={"slice": str(i)})
             for k in ("active_blocks", "free_blocks", "shared_blocks",
                       "cached_blocks")}
            for i in range(self.cache.num_slices)] \
            if self.mesh is not None else []
        if self.cache.quantized:
            _g_kv_quant_bits.set(8)
            _g_kv_quant_mult.set(round(
                quant_block_ratio(hd, compute_dt), 4))
        # self-speculative decoding (FLAGS_serving_spec, read ONCE at
        # construction): greedy-only — sampled decode has no cheap
        # accept rule that keeps outputs distribution-exact, so any
        # temperature > 0 disables the tier (documented flag matrix)
        armed_spec = (bool(flags_mod.flag("FLAGS_serving_spec"))
                      if spec is None else bool(spec))
        self.spec_tokens = max(int(
            flags_mod.flag("FLAGS_serving_spec_tokens")
            if spec_tokens is None else spec_tokens), 1)
        self.spec_ngram = max(
            int(flags_mod.flag("FLAGS_serving_spec_ngram")), 1)
        self.spec = armed_spec and temperature == 0.0
        self.prefill_token_budget = (
            flags_mod.flag("FLAGS_serving_prefill_budget")
            if prefill_token_budget is None else int(prefill_token_budget))
        self.max_queue = (flags_mod.flag("FLAGS_serving_max_queue")
                          if max_queue is None else int(max_queue))
        self.bucket_cap = (
            flags_mod.flag("FLAGS_serving_prefill_bucket_cap")
            if bucket_cap is None else int(bucket_cap))
        # prefix caching: read ONCE at construction (mid-flight flag
        # flips would mix shared and private accounting); off = the
        # cache never registers a chunk and behaves exactly as before
        self.prefix_cache = (
            bool(flags_mod.flag("FLAGS_serving_prefix_cache"))
            if prefix_cache is None else bool(prefix_cache))
        # cost attribution (profiler/accounting.py): read ONCE at
        # construction like prefix_cache; disarmed = the preallocated
        # null accountant, every hook a no-op — behavior byte-for-byte
        # pre-accounting (tools/accounting_gate.py pins both)
        armed = (bool(flags_mod.flag("FLAGS_serving_accounting"))
                 if accounting is None else bool(accounting))
        self.accounting = _accounting.Accountant(config=cfg) if armed \
            else _accounting.NULL
        # SLO burn-rate alert rules ride with accounting: evaluated at
        # step boundaries (rate-limited by FLAGS_alert_interval_s) and
        # served from the /alerts endpoint when serve_metrics attaches
        self.alerts = _alerts.AlertManager() if armed else None
        # overload control plane (serving/overload.py): deadline-aware
        # admission + priority shedding (FLAGS_serving_admission) and
        # the brownout ladder (FLAGS_serving_brownout), read ONCE at
        # construction like prefix_cache/accounting; both off = the
        # preallocated null controller, behavior byte-for-byte
        # pre-overload (tools/overload_gate.py pins the revert)
        adm = (bool(flags_mod.flag("FLAGS_serving_admission"))
               if admission is None else bool(admission))
        brw = (bool(flags_mod.flag("FLAGS_serving_brownout"))
               if brownout is None else bool(brownout))
        self.overload = _overload.OverloadController(
            admission=adm, brownout=brw) if (adm or brw) \
            else _overload.NULL
        self.queue: list[ServingRequest] = []
        self.running: dict[int, ServingRequest] = {}  # slot -> request
        self.finished: dict[int, ServingRequest] = {}  # rid -> request
        self._next_rid = 0
        self._next_admit_seq = 0
        self._last_tok = np.zeros((max_batch,), np.int64)
        self._remaining = np.zeros((max_batch,), np.int64)

    # -- submission / cancellation ------------------------------------

    def submit(self, prompt_ids, max_new_tokens=32, *, deadline=None,
               priority=None, on_token=None, on_finish=None,
               prefill_only=False):
        """Validate + enqueue; returns the ServingRequest. Raises
        ValueError on malformed or never-servable input (never corrupts
        the cache, never hangs admission), QueueFullError past the
        admission bound, and — overload control armed —
        AdmissionRejected for a provably-unmeetable deadline or a
        priority the brownout ladder's current stage refuses (both
        BEFORE any queueing: fail fast, never pay prefill for a
        request that cannot finish). ``priority`` is an int class,
        smaller = more important (default overload.NORMAL).

        ``prefill_only`` is the disaggregation prefill stage (serving/
        disagg.py): the request runs ONLY the bucket-ladder prefill and
        finishes ``DONE`` at its first token, leaving the prompt's KV
        blocks registered in the prefix index — exactly the state
        ``serving/kv_transfer.export_prefix`` serializes. It requires
        the prefix cache (without ``commit_prefix`` the blocks would
        free on finish and there would be nothing to hand off)."""
        prompt = validate_request(prompt_ids, max_new_tokens,
                                  self.max_seq_len, self.cache,
                                  who="serving.submit")
        if prefill_only and not self.prefix_cache:
            raise ValueError(
                "serving.submit: prefill_only requires the prefix "
                "cache (FLAGS_serving_prefix_cache) — finished blocks "
                "must stay registered for export")
        pri = _overload.NORMAL if priority is None else int(priority)
        if self.max_queue and len(self.queue) >= self.max_queue:
            _m_rejected.inc()
            raise QueueFullError(
                f"serving.submit: admission queue full "
                f"({len(self.queue)} >= {self.max_queue})",
                queue_depth=len(self.queue), max_queue=self.max_queue,
                retry_after_s=self.overload.queue_retry_after(self))
        # the overload gate: brownout priority floor + predictive
        # deadline rejection; also clamps max_new_tokens at stage >= 1
        # and estimates this prompt's uncovered-prefill tokens (the
        # quantity the pressure/wait predictions sum over)
        est, max_new_tokens = self.overload.admit(
            self, prompt, int(max_new_tokens), deadline, pri)
        req = ServingRequest(self._next_rid, prompt, max_new_tokens,
                             deadline=deadline, on_token=on_token,
                             on_finish=on_finish, priority=pri)
        req.est_tokens = est
        req.prefill_only = bool(prefill_only)
        self._next_rid += 1
        req.span = _tracing.start_trace(
            "serving.request", rid=req.rid, prompt_len=len(prompt),
            max_new_tokens=int(max_new_tokens))
        self.accounting.attach(req)
        self.queue.append(req)
        _g_queue.set(len(self.queue))
        return req

    def admit_handoff(self, prompt_ids, first_token, max_new_tokens=32,
                      *, deadline=None, priority=None, on_token=None,
                      on_finish=None, trace_parent=None,
                      transfer_us=0.0, transfer_bytes=0,
                      handoff_id=None):
        """Disaggregated decode-stage admission (serving/disagg.py):
        the prompt's KV blocks were just imported (``serving/
        kv_transfer.import_prefix``) and ``first_token`` was sampled by
        the prefill replica — map the imported blocks read-only and
        enter the batched decode step directly. NO prefill program runs
        on this replica (``serving.prefix.computed_tokens`` stays
        silent; tools/disagg_gate.py pins zero prefill dispatches).

        The first token re-emits HERE so the request's stream/handle
        carries the full sequence, and greedy decode from the imported
        rows is bit-identical to co-located serving. Raises
        :class:`HandoffError` (pool untouched) when the prefix is not
        fully resident or the replica has no slot/blocks — the caller
        fails open to co-located serving.

        ``trace_parent`` (a span ``context()`` dict off the prefill
        replica's ``serving.request`` root) stitches this stage's spans
        into the SAME cross-replica trace — including across a PROCESS
        boundary: a remote handoff (disagg._rpc_admit) ships the
        context in its admission rpc, so ``serving.decode_stage``
        genuinely spans hosts. ``transfer_us``/``transfer_bytes`` bill
        the fabric hop to this request's CostReport; ``handoff_id``
        (remote handoffs) rides the ``serving.handoff_admit`` span so
        the trace joins the lease/relay records."""
        prompt = validate_request(prompt_ids, max_new_tokens,
                                  self.max_seq_len, self.cache,
                                  who="serving.admit_handoff")
        if not self.prefix_cache:
            raise HandoffError(
                "serving.admit_handoff: prefix cache disarmed — "
                "imported blocks cannot be admitted")
        plan = self.cache.plan_prefix(prompt)
        if plan.covered_tokens != plan.num_tokens:
            raise HandoffError(
                f"serving.admit_handoff: imported prefix covers "
                f"{plan.covered_tokens}/{plan.num_tokens} tokens")
        if len(self.running) >= self.cache.max_batch:
            raise HandoffError(
                "serving.admit_handoff: no free decode slot")
        slot = self.cache.alloc_slot_cached(plan)
        if slot is None:
            raise HandoffError(
                "serving.admit_handoff: out of slots/blocks")
        pri = _overload.NORMAL if priority is None else int(priority)
        req = ServingRequest(self._next_rid, prompt,
                             int(max_new_tokens), deadline=deadline,
                             on_token=on_token, on_finish=on_finish,
                             priority=pri)
        self._next_rid += 1
        # stitch into the prefill replica's trace when a context rode
        # the handoff; a fresh root otherwise (unsampled/off upstream)
        child = _tracing.span("serving.decode_stage",
                              parent=trace_parent, rid=req.rid,
                              prompt_len=len(prompt))
        req.span = child if child.recording else _tracing.start_trace(
            "serving.request", rid=req.rid, prompt_len=len(prompt),
            max_new_tokens=int(max_new_tokens), stage="decode")
        self.accounting.attach(req)
        self.accounting.note_transfer(req, transfer_us, transfer_bytes)
        req.status = RequestStatus.RUNNING
        req.slot = slot
        req.admit_seq = self._next_admit_seq
        self._next_admit_seq += 1
        req.admitted_at = time.monotonic()
        self.running[slot] = req
        _m_admitted.inc()
        # imported blocks fully cover the prompt: the decode step's
        # append lands at position len(prompt) (first_token's KV row),
        # exactly the state a local prefill would have left
        self.cache.seq_lens[slot] = plan.num_tokens
        self._last_tok[slot] = int(first_token)
        self._remaining[slot] = int(max_new_tokens) - 1
        _tracing.record_span("serving.handoff_admit", req.span, 0.0,
                             hit_blocks=plan.hit_blocks,
                             transfer_bytes=int(transfer_bytes),
                             **({"handoff_id": str(handoff_id)}
                                if handoff_id is not None else {}))
        self._emit(req, int(first_token))
        self._maybe_finish(slot)
        self._update_gauges()
        return req

    def cancel(self, req):
        """Request cancellation; takes effect (blocks freed, status
        CANCELLED, stream closed) at the next step boundary."""
        if not req.done:
            req.cancel_requested = True

    @property
    def has_work(self):
        return bool(self.queue or self.running)

    def inflight(self):
        """Live (non-terminal) requests: queued + running — the number
        a drain must let finish (frontend lifecycle, /readyz body)."""
        return len(self.queue) + len(self.running)

    # -- the scheduling iteration -------------------------------------

    def step(self):
        """One iteration: sweep -> admit -> decode. Returns the list of
        (rid, token) emitted this step (prefill first tokens included)."""
        t0 = time.monotonic()
        self.accounting.step_begin()
        self._sweep()
        # overload control (serving/overload.py): pressure -> brownout
        # ladder update -> shed lowest-priority/newest queued requests
        # while over the watermarks — BEFORE admission, so a step never
        # prefills work it is about to shed
        self.overload.control(self)
        out = self._admit()
        out += self._decode()
        _m_steps.inc()
        step_us = (time.monotonic() - t0) * 1e6
        _h_step.observe(step_us)
        # apportion this step's wall time across the requests that did
        # work in it (profiler/accounting.py) BEFORE the gauges so the
        # capacity view and the attribution agree on the step boundary
        self.accounting.step_end(step_us)
        self._update_gauges()
        if self.alerts is not None:
            self.alerts.maybe_evaluate()
        return out

    def run_to_completion(self):
        """Drain everything; {rid: generated tokens} for ALL terminal
        requests (check .status for how each ended)."""
        while self.has_work:
            self.step()
        return {rid: req.generated
                for rid, req in self.finished.items()}

    # -- internals -----------------------------------------------------

    def _sweep(self):
        for req in list(self.queue):
            if req.cancel_requested:
                self.queue.remove(req)
                self._finish(req, RequestStatus.CANCELLED)
            elif req.deadline is not None and req.deadline.expired():
                self.queue.remove(req)
                self._expire(req)
        for slot, req in list(self.running.items()):
            if req.cancel_requested:
                self._finish(req, RequestStatus.CANCELLED)
            elif req.deadline is not None and req.deadline.expired():
                self._expire(req)

    def _expire(self, req):
        with _tracing.attach(req.span):  # flight record gets trace_id
            resilience.degrade("serving.deadline",
                               detail=f"rid={req.rid} "
                                      f"tokens={len(req.generated)}")
        self._finish(req, RequestStatus.TIMEOUT)

    def shed(self, req, retry_after_s=None):
        """Load-shed a QUEUED request (the overload controller's
        victim): terminal status SHED, blocks never allocated, handle
        closed with ``retry_after_s`` as the back-off hint. Survivors
        are untouched — shedding never changes a running request's
        schedule, so their greedy outputs stay bit-identical to an
        uncontended run (the preemption pin, extended)."""
        self.queue.remove(req)
        req.retry_after_s = retry_after_s
        _tracing.record_span("serving.shed", req.span, 0.0,
                             priority=req.priority,
                             queue_depth=len(self.queue))
        with _tracing.attach(req.span):  # flight record gets trace_id
            resilience.degrade(
                "serving.shed",
                detail=f"rid={req.rid} priority={req.priority} "
                       f"queue={len(self.queue)}")
        self._finish(req, RequestStatus.SHED)

    def _prefill_ids(self, req):
        # mirror of ContinuousBatchingEngine._prefill_ids — the
        # re-prefill contract (prefill of prompt+generated samples the
        # NEXT new token) must stay identical in both engines; each is
        # pinned against uncontended references by its own test file
        if not req.generated:
            return req.prompt
        return np.concatenate(
            [req.prompt,
             np.asarray(req.generated, dtype=req.prompt.dtype)])

    def _admit(self):
        """Strict FCFS: stop at the first request that doesn't fit (no
        head-of-line bypass — a small late prompt never jumps an older
        large one). Budgeted: cumulative prefill tokens per step stay
        under the budget, except the step's first admission, which is
        always allowed so an over-budget prompt still makes progress.

        Cache-aware: admission cost is the UNCOVERED tokens only — a
        request whose prefix is resident charges the budget for (and
        computes) just its tail, so cache-hitting requests admit cheaply
        and their TTFT collapses to a near-no-op. Hashing/planning works
        on the raw ids; bucket padding happens after and never reaches a
        chunk hash (serving/bucketing.py)."""
        out = []
        used = 0
        budget = self.prefill_token_budget
        bs = self.cache.block_size
        while self.queue:
            if len(self.running) >= self.cache.max_batch:
                break  # before planning: don't hash prompts every
                #        decode step while the batch stays full
            req = self.queue[0]
            ids = self._prefill_ids(req)
            ids_len = len(ids)
            plan = self.cache.plan_prefix(ids) if self.prefix_cache \
                else None
            covered = plan.covered_tokens if plan is not None else 0
            # full coverage still computes the final token for its
            # logits; everything covered is free
            uncovered = max(ids_len - covered, 1)
            if used > 0 and budget and used + uncovered > budget:
                break
            slot = self.cache.alloc_slot_cached(plan) \
                if plan is not None else self.cache.alloc_slot(ids_len)
            if slot is None:
                break
            self.queue.pop(0)
            used += uncovered
            req.slot = slot
            req.status = RequestStatus.RUNNING
            req.admit_seq = self._next_admit_seq
            self._next_admit_seq += 1
            now = time.monotonic()
            if req.admitted_at is None:
                req.admitted_at = now
                wait_us = (now - req.submitted_at) * 1e6
                with _tracing.attach(req.span):  # exemplar -> trace_id
                    _h_queue_wait.observe(wait_us)
                _tracing.record_span("serving.queue_wait", req.span,
                                     wait_us)
                self.accounting.note_queue_wait(req, wait_us)
            self.running[slot] = req
            _m_admitted.inc()
            comp0 = _compile_s()  # compile billed to THIS request
            saved0 = _saved_s()   # ...and so are AOT-cache savings
            t_pf = time.perf_counter_ns()
            if covered:
                tail_start = plan.tail_start
                pad_to = bucket_length(ids_len - tail_start, bs,
                                       self.bucket_cap,
                                       max_len=self.max_seq_len)
                with _tracing.span("serving.prefill", parent=req.span,
                                   tokens=ids_len, pad_to=pad_to,
                                   reprefill=bool(req.generated),
                                   covered=covered,
                                   hit_blocks=plan.hit_blocks):
                    tok = int(self.model.paged_prefill_extend(
                        self.cache, slot, ids, tail_start,
                        plan.write_start,
                        temperature=self.temperature, pad_to=pad_to))
            else:
                pad_to = bucket_length(ids_len, bs, self.bucket_cap,
                                       max_len=self.max_seq_len)
                with _tracing.span("serving.prefill", parent=req.span,
                                   tokens=ids_len, pad_to=pad_to,
                                   reprefill=bool(req.generated),
                                   covered=0, hit_blocks=0):
                    tok = int(self.model.paged_prefill(
                        self.cache, slot, ids,
                        temperature=self.temperature, pad_to=pad_to))
            pf_us = (time.perf_counter_ns() - t_pf) / 1000.0
            comp_us = (_compile_s() - comp0) * 1e6
            if plan is not None:
                _m_prefix_computed.inc(pad_to)
                self.cache.commit_prefix(slot, plan)
            # the prefill note carries only the COMPUTED (padded tail)
            # tokens — covered prefix tokens are free in the
            # apportionment, re-prefill bills to the preemption event
            self.accounting.note_prefill(
                req, pad_to, covered, comp_us,
                reprefill=req.preempts > 0,
                aot_saved_us=(_saved_s() - saved0) * 1e6)
            # the admission model's EWMA sees the COMPILE-FREE cost per
            # computed token — a cold bucket's compile must not poison
            # the steady-state service-time estimate
            self.overload.observe_prefill(pad_to,
                                          max(pf_us - comp_us, 0.0))
            self._last_tok[slot] = tok
            self._remaining[slot] = \
                req.max_new_tokens - len(req.generated) - 1
            if req.prefill_only:
                # disagg prefill stage: stop at the first token — the
                # decode stage continues from the handed-off blocks on
                # another replica (serving/disagg.py)
                self._remaining[slot] = 0
            self._emit(req, tok)
            out.append((req.rid, tok))
            self._maybe_finish(slot)
        return out

    def _choose_victim(self):
        """Victim choice: with the overload plane armed, lowest
        priority first, newest within a class (equal priorities reduce
        to the legacy newest-admitted order, so default-priority
        traffic is byte-for-byte unchanged); disarmed, pure
        newest-admitted (FCFS holds). Either way reclaimability-aware:
        preempting a request whose blocks are all SHARED frees
        nothing — skip past such victims to the first one whose
        eviction actually returns blocks to the pool."""
        if self.overload.shedding:
            key = lambda s: (-self.running[s].priority,  # noqa: E731
                             -self.running[s].admit_seq)
        else:
            key = lambda s: -self.running[s].admit_seq  # noqa: E731
        cands = sorted(self.running, key=key)
        for s in cands:
            if self.cache.reclaimable_blocks(s) > 0:
                return s
        return cands[0]

    def _timed_decode_dispatch(self, dispatch):
        """Run one batched decode program under the shared
        instrumentation contract — compile + AOT-saved deltas billed
        through the accountant, pure device time fed to overload
        control — so the plain and speculative paths can never drift
        apart in what they report. Returns (program output, wall us)."""
        comp0 = _compile_s()
        saved0 = _saved_s()
        t_dec = time.perf_counter_ns()
        out = dispatch()
        dec_us = (time.perf_counter_ns() - t_dec) / 1000.0
        dec_comp_us = (_compile_s() - comp0) * 1e6
        self.accounting.note_decode_compile(dec_comp_us)
        self.accounting.note_decode_aot_saved((_saved_s() - saved0) * 1e6)
        self.overload.observe_decode(max(dec_us - dec_comp_us, 0.0))
        return out, dec_us

    def _decode(self):
        if not self.running:
            return []
        if self.spec:
            out = self._decode_spec()
            if out is not None:
                return out
            # nothing proposed (or speculative capacity unavailable):
            # this step runs the plain single-token path below —
            # bit-equivalent, just not multiplied
        # make each slot's next position writable: grow tables (cold
        # cached prefixes are LRU-evicted before anything else —
        # eviction always runs before preemption), copy-on-write shared
        # blocks; preempt a victim on true pool exhaustion (never
        # truncate)
        for slot in list(self.running):
            if slot not in self.running:  # preempted as a victim below
                continue
            while True:
                denied = self.cache.prepare_append(
                    slot, int(self.cache.seq_lens[slot]) + 1)
                if denied:
                    break
                if denied.reason == CapacityError.SEQ_LIMIT:
                    # retrying can never help — only a caller bypassing
                    # validate_request's worst-case bound can get here
                    req = self.running[slot]
                    raise RuntimeError(
                        f"serving: request {req.rid} outgrew "
                        f"max_blocks_per_seq: {denied.detail}")
                if len(self.running) == 1:
                    # unreachable since validate_request bounds each
                    # request's worst-case demand to the pool; keep as
                    # an invariant guard
                    req = self.running[slot]
                    need = math.ceil(
                        (int(self.cache.seq_lens[slot]) + 1)
                        / self.cache.block_size)
                    raise RuntimeError(
                        f"serving: KV pool exhausted — request "
                        f"{req.rid} needs {need} blocks, pool has "
                        f"{self.cache.num_blocks - 1} usable and no "
                        "other running request to preempt; increase "
                        "num_blocks or lower max_seq_len")
                victim = self._choose_victim()
                self._preempt(victim)
                if victim == slot:
                    break  # grower preempted itself; re-prefills later
        if not self.running:
            return []
        active = np.zeros((self.cache.max_batch,), bool)
        for slot in self.running:
            active[slot] = True
        # decode compiles split across the batch
        toks, dec_us = self._timed_decode_dispatch(
            lambda: np.asarray(self.model.paged_decode_step(
                self.cache, np.asarray(self._last_tok), active,
                temperature=self.temperature,
                kernel_mode=self.kernel_mode)))
        out = []
        for slot, req in list(self.running.items()):
            t = int(toks[slot])
            self._last_tok[slot] = t
            self._remaining[slot] -= 1
            # the decode dispatch is one batched program: each live
            # request's trace gets a slice of that step's wall time
            _tracing.record_span("serving.decode_step", req.span,
                                 dec_us, token=len(req.generated),
                                 batch=len(self.running),
                                 route=self.kernel_route)
            self.accounting.note_decode(req)
            self._emit(req, t)
            out.append((req.rid, t))
            self._maybe_finish(slot)
        _m_decoded.inc(len(out))
        return out

    def _decode_spec(self):
        """One speculative decode iteration (docs/SERVING.md "Decode
        speed tiers"): propose up to ``spec_tokens`` draft tokens per
        running request from its OWN context (prompt-lookup n-grams,
        serving/spec.py), verify all of them in ONE batched
        multi-position paged sweep (``Llama.paged_spec_step``), accept
        the longest greedy-matching prefix per request, and roll
        rejected rows' blocks back. Greedy outputs are bit-identical
        to plain decode because every emitted token IS the sweep's own
        argmax — drafts only decide how many of those argmaxes one
        step may keep.

        Returns the (rid, token) list, or None to fall back to the
        plain path for this step: nothing proposed anywhere, or the
        pool cannot hold the speculative rows right now (the plain
        path then evicts/preempts its way forward; speculation simply
        re-engages when space returns — preemption and prefix hits
        compose, test-pinned)."""
        k = self.spec_tokens
        bs = self.cache.block_size
        drafts = {}
        any_proposed = False
        for slot, req in self.running.items():
            cap = min(k, int(self._remaining[slot]) - 1)
            d = _spec.propose_draft(self._prefill_ids(req), cap,
                                    self.spec_ngram) \
                if cap > 0 else np.empty((0,), np.int64)
            drafts[slot] = d
            any_proposed = any_proposed or d.size > 0
        if not any_proposed:
            return None
        # capacity: every slot needs positions [len, len + 1 + drafts)
        # writable (growth + COW of every touched shared block). Track
        # pre-grow block counts so a mid-loop failure rolls EVERY slot
        # back — the plain path must start from an untouched table.
        grown = []
        failed = None
        for slot in list(self.running):
            old = len(self.cache._slot_blocks[slot])
            need = int(self.cache.seq_lens[slot]) + 1 + \
                int(drafts[slot].size)
            r = self.cache.prepare_append_range(slot, need)
            if not r:
                failed = r
                break
            grown.append((slot, old))
        if failed is not None:
            for slot, old in grown:
                self.cache.truncate_blocks(slot, old)
            return None
        draft_mat = np.zeros((self.cache.max_batch, k), np.int64)
        n_inputs = np.zeros((self.cache.max_batch,), np.int64)
        active = np.zeros((self.cache.max_batch,), bool)
        for slot, d in drafts.items():
            active[slot] = True
            n_inputs[slot] = 1 + d.size
            draft_mat[slot, :d.size] = d
        outs, dec_us = self._timed_decode_dispatch(
            lambda: np.asarray(self.model.paged_spec_step(
                self.cache, np.asarray(self._last_tok), draft_mat,
                n_inputs, active)))
        out = []
        for slot, req in list(self.running.items()):
            g = outs[slot]
            proposed = int(drafts[slot].size)
            # accept while each draft equals the model's own previous
            # argmax — then the emitted run is g[0..m], exactly what m+1
            # sequential steps would have produced
            m = 0
            while m < proposed and int(draft_mat[slot, m]) == int(g[m]):
                m += 1
            emitted = [int(g[i]) for i in range(m + 1)]
            if self.eos_token_id is not None:
                for j, t in enumerate(emitted):
                    if t == self.eos_token_id:
                        # sequential decode stops here: later accepted
                        # rows must not survive
                        emitted = emitted[:j + 1]
                        m = j
                        break
            # inputs consumed = len(emitted) (last_tok + m drafts):
            # their KV rows are exactly the ones sequential decode
            # would have written; roll the rest back
            new_seq = int(self.cache.seq_lens[slot]) + len(emitted)
            self.cache.seq_lens[slot] = new_seq
            self.cache.truncate_blocks(
                slot, max(math.ceil(new_seq / bs), 1))
            self._last_tok[slot] = emitted[-1]
            self._remaining[slot] -= len(emitted)
            _m_spec_proposed.inc(proposed)
            _m_spec_accepted.inc(m)
            _m_spec_rejected.inc(proposed - m)
            if proposed:
                with _tracing.attach(req.span):  # exemplar -> trace_id
                    _h_spec_accept.observe(m / proposed)
            _tracing.record_span("serving.decode_step", req.span,
                                 dec_us, token=len(req.generated),
                                 batch=len(self.running),
                                 route=self.kernel_route,
                                 spec_proposed=proposed,
                                 spec_accepted=m)
            if proposed:
                self.accounting.note_spec(req, emitted=len(emitted),
                                          proposed=proposed, accepted=m)
            else:
                self.accounting.note_decode(req)
            for t in emitted:
                self._emit(req, t)
                out.append((req.rid, t))
            self._maybe_finish(slot)
        _m_spec_steps.inc()
        _m_decoded.inc(len(out))
        return out

    def _preempt(self, slot):
        """Free the victim's slot + blocks; requeue at the FRONT for
        re-prefill (prompt + generated) once pages free up. Greedy
        decode continues identically — pinned by test_serving.py."""
        req = self.running.pop(slot)
        self.cache.free_slot(slot)
        req.slot = -1
        req.status = RequestStatus.QUEUED
        req.preempts += 1
        self.queue.insert(0, req)
        _m_preempt.inc()
        _tracing.record_span("serving.preempt", req.span, 0.0,
                             generated=len(req.generated),
                             preempts=req.preempts)
        with _tracing.attach(req.span):  # flight record gets trace_id
            resilience.degrade(
                "serving.preempt",
                detail=f"rid={req.rid} "
                       f"len={len(req.prompt) + len(req.generated)}")

    def _emit(self, req, tok):
        req.generated.append(tok)
        now = time.monotonic()
        # SLO observations run under the request's trace context so the
        # histogram exemplar retained for the bucket names THIS trace
        with _tracing.attach(req.span):
            if req.first_token_at is None:
                req.first_token_at = now
                _h_ttft.observe((now - req.submitted_at) * 1e6)
            else:
                _h_itl.observe((now - req.last_token_at) * 1e6)
        req.last_token_at = now
        if req.on_token is not None:
            try:
                req.on_token(req, tok)
            except Exception:  # noqa: BLE001 — user cb must not kill serving
                _m_cb_errors.inc()

    def _maybe_finish(self, slot):
        req = self.running.get(slot)
        if req is None:
            return
        if self._remaining[slot] <= 0 or (
                self.eos_token_id is not None and req.generated
                and req.generated[-1] == self.eos_token_id):
            self._finish(req, RequestStatus.DONE)

    def _finish(self, req, status):
        if req.slot >= 0:
            self.cache.free_slot(req.slot)
            self.running.pop(req.slot, None)
            req.slot = -1
        req.status = status
        self.accounting.on_finish(req, status)
        _tracing.record_span("serving.terminal", req.span, 0.0,
                             terminal=status,
                             tokens=len(req.generated))
        req.span.annotate(terminal=status, tokens=len(req.generated),
                          preempts=req.preempts)
        req.span.end(status)
        self.finished[req.rid] = req
        {RequestStatus.DONE: _m_done,
         RequestStatus.CANCELLED: _m_cancelled,
         RequestStatus.TIMEOUT: _m_timeout,
         RequestStatus.SHED: _m_shed,
         RequestStatus.ERROR: _m_errors}[status].inc()
        if req.on_finish is not None:
            try:
                req.on_finish(req)
            except Exception:  # noqa: BLE001
                _m_cb_errors.inc()

    def fail_all(self, exc=None):
        """Engine died: terminate every live request with ERROR so no
        consumer blocks forever (the frontend re-raises the cause)."""
        for req in list(self.queue):
            self._finish(req, RequestStatus.ERROR)
        self.queue.clear()
        for slot in list(self.running):
            self._finish(self.running[slot], RequestStatus.ERROR)
        self._update_gauges()

    def _update_gauges(self):
        usable = self.cache.num_blocks - 1
        # num_free_blocks counts reclaimable cached blocks as free, so
        # blocks_used is blocks pinned by LIVE requests (refcount > 0)
        used = usable - self.cache.num_free_blocks()
        _g_queue.set(len(self.queue))
        _g_running.set(len(self.running))
        _g_blocks.set(used)
        _g_util.set(round(used / usable, 4) if usable else 0.0)
        _g_shared.set(self.cache.num_shared_blocks())
        _g_cached.set(self.cache.num_cached_blocks())
        # mesh-armed engines also publish the per-slice breakdown
        # (slice-labeled gauges; per-slice sums == the aggregates
        # above, pinned by tests/framework/test_mesh_serving.py)
        if self._slice_gauges:
            for i, occ in enumerate(self.cache.occupancy_slices()):
                g = self._slice_gauges[i]
                g["active_blocks"].set(occ["active"])
                g["free_blocks"].set(occ["free"])
                g["shared_blocks"].set(occ["shared"])
                g["cached_blocks"].set(occ["cached_free"])
        # armed accounting also keeps the occupancy-breakdown gauges
        # (active/free/pool-bytes) + throttled HBM sampling fresh
        self.accounting.update_capacity(self.cache)
