"""Trace-replay load generation: the workload side of the scenario
observatory (ROADMAP "Production traffic simulator").

Every gate before this drove hand-rolled corpora of a dozen prompts;
the fleet behaviors that matter at scale — diurnal ramps, burst
storms, heavy-tailed prompt lengths, shared-prefix locality, tenant
skew — were unexercised. This module generates them, deterministically:

- **Arrival processes** (``poisson`` / ``burst`` / ``ramp`` /
  ``diurnal``), composable as :class:`Phase` s of a :class:`Scenario`.
  Every arrival offset is a PURE function of ``(seed, index)`` (each
  random draw comes from its own ``numpy`` PCG64 stream keyed on
  exactly those two values), so two runs — or two processes — produce
  byte-identical schedules (tests/framework/test_loadgen.py pins this).
- **Heavy-tailed length samplers**: bounded-Pareto prompt/output
  lengths (a few giants among many dwarfs — the shape that actually
  stresses prefill budgeting and preemption).
- **Locality & mix knobs**: shared-prefix locality (a fraction of
  requests open with one of ``num_prefixes`` common prefixes —
  zipf-skewed, so the prefix cache sees realistic reuse), tenant skew,
  and a priority mix aligned with the overload plane's classes.
- **Trace records** (:class:`TraceRecord`): the JSONL interchange
  format — arrival offset, prompt spec, priority, deadline — so a
  RECORDED production trace and a synthetic one drive the exact same
  replay path (:func:`save_trace` / :func:`load_trace` round-trip,
  :func:`replay` drives any submit callable in offset order).

The scoreboard that consumes this lives in ``profiler/scorecard.py``;
the CI gate in ``tools/fleet_load_gate.py``. Nothing here touches an
engine: records are data, and :func:`replay` takes a callable.
"""

from __future__ import annotations

import json
import math

import numpy as np

__all__ = ["TraceRecord", "WorkloadSpec", "Phase", "Scenario",
           "arrival_offsets", "poisson_offsets", "burst_offsets",
           "ramp_offsets", "diurnal_offsets", "bounded_pareto",
           "prompt_ids", "prefix_tokens", "save_trace", "load_trace",
           "dumps_trace", "loads_trace", "replay"]

# stream-domain salts: every independent draw family gets its own
# lane so adding a knob never perturbs another knob's stream
_SALT_GAP = 1
_SALT_PLEN = 2
_SALT_OUT = 3
_SALT_LOCAL = 4
_SALT_PREFIX = 5
_SALT_TENANT = 6
_SALT_PRI = 7
_SALT_TAIL = 8
_SALT_JITTER = 9
# prefix token content depends on prefix_id ONLY (never the scenario
# seed): two scenarios hitting prefix 3 share bytes, like two tenants
# sharing a system prompt
_PREFIX_CONTENT_SALT = 0x5EED


def _rng(seed, index, salt):
    """One PCG64 stream per (seed, index, salt) — the determinism
    contract: any sampled quantity is a pure function of exactly these
    three ints, reproducible across runs, processes, and platforms
    (numpy SeedSequence is specified, not OS-dependent)."""
    return np.random.default_rng([int(seed), int(index), int(salt)])


def _u(seed, index, salt):
    """One uniform (0, 1] draw from that stream (never exactly 0 —
    safe as a Pareto/exponential denominator)."""
    return 1.0 - float(_rng(seed, index, salt).random())


# -- arrival processes -----------------------------------------------------

def poisson_offsets(n, rate_rps, seed, start=0.0):
    """Homogeneous Poisson arrivals: i.i.d. exponential gaps at
    ``rate_rps``. Offsets are a prefix-sum of per-index pure draws, so
    offset[i] is itself a pure function of (seed, i)."""
    out, t = [], float(start)
    for i in range(int(n)):
        t += -math.log(_u(seed, i, _SALT_GAP)) / float(rate_rps)
        out.append(t)
    return out


def burst_offsets(n, duration_s, seed, start=0.0):
    """A storm: ``n`` arrivals compressed into ``duration_s``, evenly
    spaced with sub-spacing jitter (monotone by construction — replay
    order equals index order)."""
    n = int(n)
    space = float(duration_s) / max(n, 1)
    return [float(start) + space * i
            + space * 0.5 * _u(seed, i, _SALT_JITTER)
            for i in range(n)]


def ramp_offsets(n, duration_s, seed, start=0.0):
    """Linearly increasing intensity from ~0 to peak over
    ``duration_s`` (inverse-CDF of a triangular density: offsets go as
    sqrt(u), jittered within their slot)."""
    n = int(n)
    out = []
    for i in range(n):
        u = (i + 0.5 * _u(seed, i, _SALT_JITTER)) / max(n, 1)
        out.append(float(start) + float(duration_s) * math.sqrt(u))
    return out


def diurnal_offsets(n, period_s, seed, start=0.0, depth=0.8):
    """One day-shaped cycle: intensity ``1 + depth*sin`` over
    ``period_s``, arrivals by inverse-CDF (bisection — deterministic).
    ``depth`` in [0, 1): 0 is flat, near 1 swings from near-silent
    trough to double-rate peak."""
    n = int(n)
    period = float(period_s)
    depth = float(depth)

    def cdf(t):  # integral of (1 + depth*sin(2*pi*t/P)) / P, in [0,1]
        w = 2.0 * math.pi / period
        return (t + depth * (1.0 - math.cos(w * t)) / w) / period

    out = []
    for i in range(n):
        u = (i + 0.5 * _u(seed, i, _SALT_JITTER)) / max(n, 1)
        lo, hi = 0.0, period
        for _ in range(40):  # ~1e-12 * period resolution
            mid = 0.5 * (lo + hi)
            if cdf(mid) < u:
                lo = mid
            else:
                hi = mid
        out.append(float(start) + 0.5 * (lo + hi))
    return out


_ARRIVALS = {"poisson": poisson_offsets, "burst": burst_offsets,
             "ramp": ramp_offsets, "diurnal": diurnal_offsets}


def arrival_offsets(kind, n, scale, seed, start=0.0, **kw):
    """Dispatch one arrival process by name. ``scale`` is the kind's
    natural second positional (``rate_rps`` for poisson,
    ``duration_s`` for burst/ramp, ``period_s`` for diurnal). Unknown
    kinds raise — a typo'd scenario must not silently fall back to
    anything."""
    try:
        fn = _ARRIVALS[kind]
    except KeyError:
        raise ValueError(f"unknown arrival process {kind!r}; "
                         f"one of {sorted(_ARRIVALS)}") from None
    return fn(n, scale, seed, start=start, **kw)


# -- samplers --------------------------------------------------------------

def bounded_pareto(u, alpha, lo, hi):
    """Inverse-CDF of the bounded Pareto on [lo, hi] with tail index
    ``alpha`` (smaller alpha = heavier tail) for one uniform draw
    ``u`` in (0, 1]. Pure math — the caller owns the stream."""
    lo, hi = float(lo), float(hi)
    if hi <= lo:
        return lo
    la, ha = lo ** alpha, hi ** alpha
    return (-(u * ha - u * la - ha) / (ha * la)) ** (-1.0 / alpha)


def _weighted_choice(u, weights):
    """Pick a key from ``{key: weight}`` by one uniform draw, keys in
    sorted order (dict insertion order must not leak into schedules)."""
    items = sorted(weights.items(), key=lambda kv: str(kv[0]))
    total = float(sum(w for _, w in items))
    acc = 0.0
    for k, w in items:
        acc += w / total
        if u <= acc:
            return k
    return items[-1][0]


class WorkloadSpec:
    """Per-phase workload shape: length distributions, shared-prefix
    locality, tenant skew, priority mix. All knobs have serving-shaped
    defaults; everything is sampled through the (seed, index) streams,
    never from shared RNG state."""

    def __init__(self, *,
                 prompt_len=(4, 48), prompt_alpha=1.2,
                 max_new_tokens=(2, 8), output_alpha=1.5,
                 locality=0.0, num_prefixes=4, prefix_len=8,
                 tenants=None, priority_mix=None, deadlines=None,
                 vocab=255):
        self.prompt_len = (int(prompt_len[0]), int(prompt_len[1]))
        self.prompt_alpha = float(prompt_alpha)
        self.max_new_tokens = (int(max_new_tokens[0]),
                               int(max_new_tokens[1]))
        self.output_alpha = float(output_alpha)
        self.locality = float(locality)
        self.num_prefixes = int(num_prefixes)
        self.prefix_len = int(prefix_len)
        # zipf-ish tenant skew by default: one hot tenant, a warm one,
        # a long cold tail
        self.tenants = dict(tenants) if tenants else \
            {"t0": 4.0, "t1": 2.0, "t2": 1.0}
        # priorities use the overload plane's classes (HIGH=0 .. LOW=2)
        self.priority_mix = dict(priority_mix) if priority_mix else \
            {0: 0.25, 1: 0.5, 2: 0.25}
        # per-priority deadline (None = no deadline for that class)
        self.deadlines = dict(deadlines) if deadlines else \
            {0: 300.0, 1: None, 2: None}
        self.vocab = int(vocab)

    def sample(self, seed, index):
        """All non-arrival fields of record ``index``: lengths, prefix
        assignment, tenant, priority — each from its own stream."""
        lo, hi = self.prompt_len
        plen = int(round(bounded_pareto(
            _u(seed, index, _SALT_PLEN), self.prompt_alpha, lo, hi)))
        olo, ohi = self.max_new_tokens
        new = int(round(bounded_pareto(
            _u(seed, index, _SALT_OUT), self.output_alpha, olo, ohi)))
        prefix_id, prefix_len = None, 0
        if self.locality > 0 and self.num_prefixes > 0 and \
                _u(seed, index, _SALT_LOCAL) <= self.locality:
            # zipf-skewed prefix popularity: weight 1/(1+rank)
            weights = {pid: 1.0 / (1 + pid)
                       for pid in range(self.num_prefixes)}
            prefix_id = _weighted_choice(
                _u(seed, index, _SALT_PREFIX), weights)
            prefix_len = min(self.prefix_len, max(plen - 1, 1))
        tenant = _weighted_choice(_u(seed, index, _SALT_TENANT),
                                  self.tenants)
        priority = _weighted_choice(_u(seed, index, _SALT_PRI),
                                    self.priority_mix)
        return {"prompt_len": max(plen, 1), "max_new_tokens": max(new, 1),
                "prefix_id": prefix_id, "prefix_len": prefix_len,
                "tenant": str(tenant), "priority": int(priority),
                "deadline_s": self.deadlines.get(priority)}


# -- trace records ---------------------------------------------------------

_FIELDS = ("offset_s", "prompt_len", "max_new_tokens", "priority",
           "deadline_s", "tenant", "prefix_id", "prefix_len", "seed",
           "index", "phase")


class TraceRecord:
    """One arrival: WHEN (``offset_s`` from scenario start), WHAT
    (prompt spec: length, shared-prefix assignment, materialization
    seed), and UNDER WHICH CONTRACT (priority, deadline, tenant).
    Plain data — ``as_dict``/``from_dict`` round-trip through JSONL
    byte-identically (sorted keys), which is what lets a recorded
    production trace replace a synthetic schedule."""

    __slots__ = _FIELDS

    def __init__(self, offset_s, prompt_len, max_new_tokens=4,
                 priority=1, deadline_s=None, tenant="t0",
                 prefix_id=None, prefix_len=0, seed=0, index=0,
                 phase=""):
        self.offset_s = float(offset_s)
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.priority = int(priority)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.tenant = str(tenant)
        self.prefix_id = None if prefix_id is None else int(prefix_id)
        self.prefix_len = int(prefix_len)
        self.seed = int(seed)
        self.index = int(index)
        self.phase = str(phase)

    def as_dict(self):
        return {f: getattr(self, f) for f in _FIELDS}

    @classmethod
    def from_dict(cls, d):
        return cls(**{f: d[f] for f in _FIELDS if f in d})

    def __eq__(self, other):
        return isinstance(other, TraceRecord) and \
            self.as_dict() == other.as_dict()

    def __repr__(self):
        return (f"TraceRecord(offset_s={self.offset_s:.4f}, "
                f"prompt_len={self.prompt_len}, pri={self.priority}, "
                f"tenant={self.tenant!r}, prefix={self.prefix_id}, "
                f"phase={self.phase!r})")


def prefix_tokens(prefix_id, prefix_len, vocab=255):
    """The shared prefix's token content — a function of ``prefix_id``
    ONLY, so every request (any scenario, any seed) opening with
    prefix ``k`` presents identical leading tokens and the paged
    engine's prefix cache can share their KV blocks."""
    rng = np.random.default_rng([_PREFIX_CONTENT_SALT, int(prefix_id)])
    return rng.integers(0, int(vocab), (int(prefix_len),)).astype("int64")


def prompt_ids(record, vocab=255):
    """Materialize a record's prompt: shared prefix (if assigned) +
    a per-record tail. Deterministic — same record, same tokens."""
    tail_len = record.prompt_len - record.prefix_len
    tail = _rng(record.seed, record.index, _SALT_TAIL).integers(
        0, int(vocab), (max(tail_len, 0),)).astype("int64")
    if record.prefix_id is None or record.prefix_len <= 0:
        return tail
    return np.concatenate(
        [prefix_tokens(record.prefix_id, record.prefix_len, vocab), tail])


# -- scenarios -------------------------------------------------------------

class Phase:
    """One leg of a scenario: ``n`` arrivals from one arrival process,
    drawn against one :class:`WorkloadSpec`. ``arrival_kw`` feeds the
    process (``rate_rps`` for poisson; ``duration_s`` for burst/ramp;
    ``period_s`` for diurnal). ``action`` is an opaque tag the
    scoreboard interprets mid-phase (e.g. ``"kill:r1"`` /
    ``"drain:r0"``) — data, not behavior, so it replays from JSONL."""

    def __init__(self, name, n, arrival="poisson", workload=None,
                 action=None, **arrival_kw):
        self.name = str(name)
        self.n = int(n)
        self.arrival = str(arrival)
        self.workload = workload or WorkloadSpec()
        self.action = action
        self.arrival_kw = dict(arrival_kw)

    def offsets(self, seed, start=0.0):
        kw = dict(self.arrival_kw)
        if self.arrival == "poisson":
            scale = kw.pop("rate_rps", 50.0)
        elif self.arrival in ("burst", "ramp"):
            scale = kw.pop("duration_s", 0.1)
        else:
            scale = kw.pop("period_s", 1.0)
        return arrival_offsets(self.arrival, self.n, scale, seed,
                               start=start, **kw)


class Scenario:
    """A named composition of phases. ``schedule(seed)`` lays the
    phases end-to-end on one clock and returns the flat
    ``list[TraceRecord]`` in arrival order — the ONLY thing the replay
    path consumes, so a loaded JSONL trace is a first-class schedule."""

    def __init__(self, name, phases):
        self.name = str(name)
        self.phases = list(phases)

    def schedule(self, seed):
        records, t0, index = [], 0.0, 0
        for phase in self.phases:
            offs = phase.offsets(seed, start=t0)
            for off in offs:
                fields = phase.workload.sample(seed, index)
                records.append(TraceRecord(
                    offset_s=off, seed=seed, index=index,
                    phase=phase.name, **fields))
                index += 1
            t0 = max([t0, *offs]) if offs else t0
        return records


# -- JSONL trace IO --------------------------------------------------------

def dumps_trace(records):
    """Records to JSONL text (sorted keys, one record per line) — the
    byte-identity surface the determinism tests pin."""
    return "".join(json.dumps(r.as_dict(), sort_keys=True) + "\n"
                   for r in records)


def loads_trace(text):
    return [TraceRecord.from_dict(json.loads(line))
            for line in text.splitlines() if line.strip()]


def save_trace(records, path):
    with open(path, "w") as f:
        f.write(dumps_trace(records))


def load_trace(path):
    with open(path) as f:
        return loads_trace(f.read())


# -- replay ----------------------------------------------------------------

def replay(records, submit, *, between=None, time_scale=0.0):
    """Drive ``submit(record)`` in arrival order. ``time_scale``
    stretches the recorded offsets into real sleeps (0.0 — the gate
    default — replays as-fast-as-possible: offset ORDER is the
    contract, wall time is not); ``between`` is called after each
    submit (foreground engines use it to take scheduler steps, so
    arrivals interleave with decode like they would under real load).

    Returns ``[(record, handle_or_exception), ...]``: a submit that
    raises (AdmissionRejected, QueueFullError, NoReplicaAvailable) is
    an OUTCOME under load, not a replay failure."""
    import time as _time

    out, prev = [], None
    for rec in sorted(records, key=lambda r: (r.offset_s, r.index)):
        if time_scale > 0.0 and prev is not None and \
                rec.offset_s > prev:
            _time.sleep((rec.offset_s - prev) * time_scale)
        prev = rec.offset_s
        try:
            out.append((rec, submit(rec)))
        except Exception as e:  # noqa: BLE001 — rejection is data here
            out.append((rec, e))
        if between is not None:
            between()
    return out
