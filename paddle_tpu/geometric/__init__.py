"""`paddle.geometric` (reference: python/paddle/geometric/ — graph
message passing + segment ops over phi graph_send_recv kernels).
TPU-first: scatter-adds (`at[].add/max/min`) — XLA lowers these to sorted
segment ops."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply, unwrap

__all__ = ["send_u_recv", "send_ue_recv", "segment_sum", "segment_mean",
           "segment_max", "segment_min",
           "reindex_graph", "reindex_heter_graph", "sample_neighbors",
           "weighted_sample_neighbors", "send_uv"]


def _out_size(dst, out_size):
    if out_size is not None:
        return int(out_size)
    return int(unwrap(dst).max()) + 1


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    n = _out_size(dst_index, out_size)

    def fn(a, src, dst):
        msgs = a[src]
        shape = (n,) + a.shape[1:]
        if reduce_op == "sum":
            return jnp.zeros(shape, a.dtype).at[dst].add(msgs)
        if reduce_op == "mean":
            s = jnp.zeros(shape, a.dtype).at[dst].add(msgs)
            cnt = jnp.zeros((n,), a.dtype).at[dst].add(1.0)
            return s / jnp.maximum(cnt, 1.0).reshape(
                (n,) + (1,) * (a.ndim - 1))
        if reduce_op == "max":
            init = jnp.full(shape, -jnp.inf, a.dtype)
            out = init.at[dst].max(msgs)
            return jnp.where(jnp.isinf(out), 0.0, out)
        if reduce_op == "min":
            init = jnp.full(shape, jnp.inf, a.dtype)
            out = init.at[dst].min(msgs)
            return jnp.where(jnp.isinf(out), 0.0, out)
        raise ValueError(reduce_op)

    return apply(fn, x, src_index, dst_index, name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    n = _out_size(dst_index, out_size)

    def fn(a, e, src, dst):
        msgs = a[src]
        if message_op == "add":
            msgs = msgs + e
        elif message_op == "mul":
            msgs = msgs * e
        shape = (n,) + msgs.shape[1:]
        if reduce_op == "sum":
            return jnp.zeros(shape, msgs.dtype).at[dst].add(msgs)
        if reduce_op == "mean":
            s = jnp.zeros(shape, msgs.dtype).at[dst].add(msgs)
            cnt = jnp.zeros((n,), msgs.dtype).at[dst].add(1.0)
            return s / jnp.maximum(cnt, 1.0).reshape(
                (n,) + (1,) * (msgs.ndim - 1))
        if reduce_op == "max":
            out = jnp.full(shape, -jnp.inf, msgs.dtype).at[dst].max(msgs)
            return jnp.where(jnp.isinf(out), 0.0, out)
        if reduce_op == "min":
            out = jnp.full(shape, jnp.inf, msgs.dtype).at[dst].min(msgs)
            return jnp.where(jnp.isinf(out), 0.0, out)
        raise ValueError(reduce_op)

    return apply(fn, x, y, src_index, dst_index, name="send_ue_recv")


def _segment(x, segment_ids, mode):
    n = int(unwrap(segment_ids).max()) + 1

    def fn(a, seg):
        shape = (n,) + a.shape[1:]
        if mode == "sum":
            return jnp.zeros(shape, a.dtype).at[seg].add(a)
        if mode == "mean":
            s = jnp.zeros(shape, a.dtype).at[seg].add(a)
            cnt = jnp.zeros((n,), a.dtype).at[seg].add(1.0)
            return s / jnp.maximum(cnt, 1.0).reshape(
                (n,) + (1,) * (a.ndim - 1))
        if mode == "max":
            out = jnp.full(shape, -jnp.inf, a.dtype).at[seg].max(a)
            return jnp.where(jnp.isinf(out), 0.0, out)
        out = jnp.full(shape, jnp.inf, a.dtype).at[seg].min(a)
        return jnp.where(jnp.isinf(out), 0.0, out)

    return apply(fn, x, segment_ids, name=f"segment_{mode}")


def segment_sum(x, segment_ids, name=None):
    return _segment(x, segment_ids, "sum")


def segment_mean(x, segment_ids, name=None):
    return _segment(x, segment_ids, "mean")


def segment_max(x, segment_ids, name=None):
    return _segment(x, segment_ids, "max")


def segment_min(x, segment_ids, name=None):
    return _segment(x, segment_ids, "min")


def reindex_graph(x, neighbors, count, value_buffer=None,
                  index_buffer=None, name=None):
    """Compact global node ids to local ids (reference reindex_graph):
    returns (reindexed src, reindexed dst, out_nodes)."""
    import numpy as np

    from ..core.dispatch import unwrap
    from ..core.tensor import Tensor

    xs = np.asarray(unwrap(x)).reshape(-1)
    nb = np.asarray(unwrap(neighbors)).reshape(-1)
    cnt = np.asarray(unwrap(count)).reshape(-1)
    out_nodes = list(xs)
    seen = {int(v): i for i, v in enumerate(xs)}
    for v in nb:
        v = int(v)
        if v not in seen:
            seen[v] = len(out_nodes)
            out_nodes.append(v)
    reindex_src = np.asarray([seen[int(v)] for v in nb], np.int64)
    reindex_dst = np.repeat(np.arange(len(xs), dtype=np.int64), cnt)
    return (Tensor(reindex_src), Tensor(reindex_dst),
            Tensor(np.asarray(out_nodes, np.int64)))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: neighbors/count are lists per edge type."""
    import numpy as np

    from ..core.dispatch import unwrap
    from ..core.tensor import Tensor

    xs = np.asarray(unwrap(x)).reshape(-1)
    seen = {int(v): i for i, v in enumerate(xs)}
    out_nodes = list(xs)
    srcs, dsts = [], []
    for nb_t, cnt_t in zip(neighbors, count):
        nb = np.asarray(unwrap(nb_t)).reshape(-1)
        cnt = np.asarray(unwrap(cnt_t)).reshape(-1)
        for v in nb:
            v = int(v)
            if v not in seen:
                seen[v] = len(out_nodes)
                out_nodes.append(v)
        srcs.append(np.asarray([seen[int(v)] for v in nb], np.int64))
        dsts.append(np.repeat(np.arange(len(xs), dtype=np.int64), cnt))
    return (Tensor(np.concatenate(srcs)), Tensor(np.concatenate(dsts)),
            Tensor(np.asarray(out_nodes, np.int64)))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """CSC neighbor sampling (reference geometric.sample_neighbors)."""
    import numpy as np

    from ..core.dispatch import unwrap
    from ..core.tensor import Tensor

    r = np.asarray(unwrap(row)).reshape(-1)
    cp = np.asarray(unwrap(colptr)).reshape(-1)
    nodes = np.asarray(unwrap(input_nodes)).reshape(-1)
    out, counts = [], []
    for v in nodes:
        lo, hi = int(cp[int(v)]), int(cp[int(v) + 1])
        neigh = r[lo:hi]
        if 0 <= sample_size < len(neigh):
            neigh = np.random.choice(neigh, sample_size, replace=False)
        out.append(neigh)
        counts.append(len(neigh))
    return (Tensor(np.concatenate(out).astype(np.int64) if out
                   else np.zeros(0, np.int64)),
            Tensor(np.asarray(counts, np.int64)))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None,
                              return_eids=False, name=None):
    """Weight-proportional neighbor sampling."""
    import numpy as np

    from ..core.dispatch import unwrap
    from ..core.tensor import Tensor

    r = np.asarray(unwrap(row)).reshape(-1)
    cp = np.asarray(unwrap(colptr)).reshape(-1)
    w = np.asarray(unwrap(edge_weight)).reshape(-1)
    nodes = np.asarray(unwrap(input_nodes)).reshape(-1)
    out, counts = [], []
    for v in nodes:
        lo, hi = int(cp[int(v)]), int(cp[int(v) + 1])
        neigh = r[lo:hi]
        wv = w[lo:hi]
        if 0 <= sample_size < len(neigh):
            pvals = wv / wv.sum()
            neigh = np.random.choice(neigh, sample_size, replace=False,
                                     p=pvals)
        out.append(neigh)
        counts.append(len(neigh))
    return (Tensor(np.concatenate(out).astype(np.int64) if out
                   else np.zeros(0, np.int64)),
            Tensor(np.asarray(counts, np.int64)))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from src node features x and dst node features y
    (reference send_uv)."""
    import jax.numpy as jnp

    from ..core.dispatch import apply, as_index, unwrap

    src = as_index(unwrap(src_index))
    dst = as_index(unwrap(dst_index))
    ops_map = {"add": jnp.add, "sub": jnp.subtract,
               "mul": jnp.multiply, "div": jnp.divide}
    op = ops_map[message_op]

    def fn(a, b):
        return op(a[src], b[dst])
    return apply(fn, x, y, name="send_uv")
