"""`paddle.geometric` (reference: python/paddle/geometric/ — graph
message passing + segment ops over phi graph_send_recv kernels).
TPU-first: scatter-adds (`at[].add/max/min`) — XLA lowers these to sorted
segment ops."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply, unwrap

__all__ = ["send_u_recv", "send_ue_recv", "segment_sum", "segment_mean",
           "segment_max", "segment_min"]


def _out_size(dst, out_size):
    if out_size is not None:
        return int(out_size)
    return int(unwrap(dst).max()) + 1


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    n = _out_size(dst_index, out_size)

    def fn(a, src, dst):
        msgs = a[src]
        shape = (n,) + a.shape[1:]
        if reduce_op == "sum":
            return jnp.zeros(shape, a.dtype).at[dst].add(msgs)
        if reduce_op == "mean":
            s = jnp.zeros(shape, a.dtype).at[dst].add(msgs)
            cnt = jnp.zeros((n,), a.dtype).at[dst].add(1.0)
            return s / jnp.maximum(cnt, 1.0).reshape(
                (n,) + (1,) * (a.ndim - 1))
        if reduce_op == "max":
            init = jnp.full(shape, -jnp.inf, a.dtype)
            out = init.at[dst].max(msgs)
            return jnp.where(jnp.isinf(out), 0.0, out)
        if reduce_op == "min":
            init = jnp.full(shape, jnp.inf, a.dtype)
            out = init.at[dst].min(msgs)
            return jnp.where(jnp.isinf(out), 0.0, out)
        raise ValueError(reduce_op)

    return apply(fn, x, src_index, dst_index, name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    n = _out_size(dst_index, out_size)

    def fn(a, e, src, dst):
        msgs = a[src]
        if message_op == "add":
            msgs = msgs + e
        elif message_op == "mul":
            msgs = msgs * e
        shape = (n,) + msgs.shape[1:]
        if reduce_op == "sum":
            return jnp.zeros(shape, msgs.dtype).at[dst].add(msgs)
        if reduce_op == "mean":
            s = jnp.zeros(shape, msgs.dtype).at[dst].add(msgs)
            cnt = jnp.zeros((n,), msgs.dtype).at[dst].add(1.0)
            return s / jnp.maximum(cnt, 1.0).reshape(
                (n,) + (1,) * (msgs.ndim - 1))
        if reduce_op == "max":
            out = jnp.full(shape, -jnp.inf, msgs.dtype).at[dst].max(msgs)
            return jnp.where(jnp.isinf(out), 0.0, out)
        if reduce_op == "min":
            out = jnp.full(shape, jnp.inf, msgs.dtype).at[dst].min(msgs)
            return jnp.where(jnp.isinf(out), 0.0, out)
        raise ValueError(reduce_op)

    return apply(fn, x, y, src_index, dst_index, name="send_ue_recv")


def _segment(x, segment_ids, mode):
    n = int(unwrap(segment_ids).max()) + 1

    def fn(a, seg):
        shape = (n,) + a.shape[1:]
        if mode == "sum":
            return jnp.zeros(shape, a.dtype).at[seg].add(a)
        if mode == "mean":
            s = jnp.zeros(shape, a.dtype).at[seg].add(a)
            cnt = jnp.zeros((n,), a.dtype).at[seg].add(1.0)
            return s / jnp.maximum(cnt, 1.0).reshape(
                (n,) + (1,) * (a.ndim - 1))
        if mode == "max":
            out = jnp.full(shape, -jnp.inf, a.dtype).at[seg].max(a)
            return jnp.where(jnp.isinf(out), 0.0, out)
        out = jnp.full(shape, jnp.inf, a.dtype).at[seg].min(a)
        return jnp.where(jnp.isinf(out), 0.0, out)

    return apply(fn, x, segment_ids, name=f"segment_{mode}")


def segment_sum(x, segment_ids, name=None):
    return _segment(x, segment_ids, "sum")


def segment_mean(x, segment_ids, name=None):
    return _segment(x, segment_ids, "mean")


def segment_max(x, segment_ids, name=None):
    return _segment(x, segment_ids, "max")


def segment_min(x, segment_ids, name=None):
    return _segment(x, segment_ids, "min")
