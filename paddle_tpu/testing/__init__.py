"""Test-support machinery shipped inside the package (reference
`paddle.base.core` exposes its fault hooks the same way: injection must
live where the product code can call it, not in tests/).

`paddle_tpu.testing.faults` — deterministic, named fault-injection
sites; see docs/ROBUSTNESS.md for the site catalog.
"""

from . import faults  # noqa: F401

__all__ = ["faults"]
