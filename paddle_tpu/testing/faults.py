"""Deterministic fault injection: named sites, armed per test.

Recovery code is only trustworthy if every path is *driven*, and real
faults (a disk dying mid-fsync, a peer refusing a connect, XLA throwing
RESOURCE_EXHAUSTED) are not reproducible in CI. Product code therefore
declares **named injection sites** at the exact points where those
faults strike::

    from ..testing import faults
    ...
    faults.site("checkpoint.write_shards")   # may raise when armed
    np.savez(tmp_path, **arrays)

and chaos tests (tests/framework/test_chaos.py, tools/chaos_gate.py)
arm them deterministically::

    with faults.inject("checkpoint.write_shards", nth=1,
                       exc=faults.FaultInjected):
        ckpt.save_state_dict(sd, path)       # "crashes" mid-write

Design rules:

- **Compiled out when idle.** ``site()`` is a single module-global
  boolean read unless at least one injection is armed — the hot paths
  that carry sites (deferred flush) pay nothing in production.
- **Deterministic.** An injection fires on the ``nth`` hit of its site
  (1-based, counted from arming) and on the ``count - 1`` hits after
  it; no randomness, so a chaos scenario replays exactly.
- **Raise or delay.** ``exc`` may be an exception instance, an
  exception class, or a zero-arg callable returning either; ``delay``
  sleeps (for racing-timeout scenarios) before any raise.

The site catalog lives in docs/ROBUSTNESS.md; a site string is API —
renaming one breaks the chaos corpus.
"""

from __future__ import annotations

import contextlib
import threading
import time

__all__ = ["FaultInjected", "site", "inject", "arm", "disarm", "clear",
           "hits", "fired", "active"]


class FaultInjected(RuntimeError):
    """Default exception raised at an armed site."""


class _Injection:
    __slots__ = ("name", "nth", "count", "exc", "delay", "fired")

    def __init__(self, name, nth, count, exc, delay):
        self.name = name
        self.nth = int(nth)
        self.count = int(count)
        self.exc = exc
        self.delay = float(delay)
        self.fired = 0


# the idle-path contract: site() reads ONE module global and returns.
# _ENABLED is true iff _ARMED is non-empty; all bookkeeping is locked.
_ENABLED = False
_lock = threading.Lock()
_ARMED: dict[str, _Injection] = {}
_HITS: dict[str, int] = {}


def site(name):
    """Declare an injection point. No-op unless a fault is armed
    somewhere; raises / sleeps when ``name``'s injection triggers."""
    if not _ENABLED:
        return
    _hit(name)


def _hit(name):
    with _lock:
        n = _HITS.get(name, 0) + 1
        _HITS[name] = n
        inj = _ARMED.get(name)
        if inj is None or n < inj.nth or inj.fired >= inj.count:
            return
        inj.fired += 1
        delay, exc = inj.delay, inj.exc
    if delay:
        time.sleep(delay)
    if exc is None:
        return
    e = exc() if callable(exc) else exc
    if isinstance(e, BaseException):
        raise e


def arm(name, nth=1, exc=FaultInjected, delay=0.0, count=1):
    """Arm ``name``: hits ``nth`` .. ``nth+count-1`` (counted from this
    call) trigger. Returns the injection record (``.fired`` observable)."""
    global _ENABLED
    inj = _Injection(name, nth, count, exc, delay)
    with _lock:
        _ARMED[name] = inj
        _HITS[name] = 0
        _ENABLED = True
    return inj


def disarm(name):
    global _ENABLED
    with _lock:
        _ARMED.pop(name, None)
        if not _ARMED:
            _ENABLED = False


def clear():
    """Disarm everything and zero hit counters."""
    global _ENABLED
    with _lock:
        _ARMED.clear()
        _HITS.clear()
        _ENABLED = False


@contextlib.contextmanager
def inject(name, nth=1, exc=FaultInjected, delay=0.0, count=1):
    """Context-manager arming: disarms on exit however the body ends."""
    inj = arm(name, nth=nth, exc=exc, delay=delay, count=count)
    try:
        yield inj
    finally:
        disarm(name)


def hits(name):
    """Hits of ``name`` since it was last armed (0 when never armed —
    hits are only counted while injection is enabled)."""
    with _lock:
        return _HITS.get(name, 0)


def fired(name):
    with _lock:
        inj = _ARMED.get(name)
        return inj.fired if inj is not None else 0


def active():
    with _lock:
        return sorted(_ARMED)
